"""Device-accelerated spill-tree passes (dense cosine decomposition).

The spill tree's host cost is NOT one big matmul — it is hundreds of
sample-sized BLAS passes (farthest-point traversal, Lloyd refinement,
the sampled rejection screen, greedy leader cover, canopy membership),
measured at ~2/3 of the cosine anchor's wall on the single-core host
(VERDICT r4 item 2). This module runs those passes on the accelerator:
the node's rows are uploaded ONCE (bf16), every sequential traversal
becomes a `lax.while_loop` of matvecs against the resident rows, and
only small results cross the link — pivot vectors [m, D], assignment
bytes [n], packed membership bits [n*m/8], a leader adjacency [L, L].

Precision contract: rows are stored bf16 (halves the upload — the
tunnel's ~60 MB/s uplink is the binding resource, see BASELINE.md), and
every band comparison the COVERAGE PROOF depends on is inflated by an
explicit `slack` bound on the bf16 chord error (2*2^-9 dot error for
unit rows -> chord error <= sqrt(2*2^-8) at small chords). Inflating a
band is one-sided: the copy-sets/canopies only GROW, so no accepted
pair is ever missed — quantization costs duplication, never
correctness. Pivot SELECTION and the rejection screen need no slack at
all (pivot choice never affects correctness; the screen only decides
whether to escalate, and the exact full-node pass re-decides).

Reference analog: none — the reference's decomposition is 2-D
rectangles on a driver-local grid (EvenSplitPartitioner.scala:66-103);
this is the high-dimensional counterpart's hot path moved to the chip.

Dimension contract: every pass here is written against generic unit
rows ``[N, D]`` — chord arithmetic is dot products, pivots are
synthetic unit vectors, the level build's node tables carry ``dim`` as
a plain static — so the SAME tree serves the 512-d cosine route, the
sparse TF-IDF route, and the embed engine's spill fallback at any
D in 2..4096 (the bf16 slack bound above). The only shape requirement
is the explicit rank-2 guard in :meth:`DeviceNodeOps.from_host`;
nothing assumes D == 2 (the reference's grid world), and
``tests/test_embed.py`` pins D=64 parity so the embed fallback can
reuse the tree unmodified.
"""

from __future__ import annotations

import functools
import time

import numpy as np

from dbscan_tpu import faults, obs
from dbscan_tpu.obs import compile as obs_compile
from dbscan_tpu.obs import memory as obs_memory

# chord-error bound for bf16-stored unit rows: |dot error| <= 2*2^-9
# (+f32 accumulation, negligible at D<=4096); chord = sqrt(2-2dot) moves
# worst at small chords by sqrt(2 * 2 * 2^-9) ~ 0.0885
BF16_CHORD_SLACK = float(np.sqrt(2.0 * 2.0 * 2.0**-9)) + 1e-4
_LEADER_CAP = 4096  # mirrors spill._LEADER_CAP


class DeviceNodeOps:
    """One spill node's rows resident on the accelerator.

    Drop-in companion to spill._DenseOps for the device-accelerated
    passes; built lazily by the tree driver only when a usable non-CPU
    backend exists (or when forced for tests). ``take`` gathers a child
    subset ON DEVICE from the parent's resident rows — a child upload is
    an int32 index vector, ~500x smaller than its rows."""

    def __init__(self, x, n: int, dim: int):
        self.x = x  # [n, D] bf16 device array
        self.n = n
        self.dim = dim

    @classmethod
    def from_host(cls, x_host: np.ndarray):
        import jax.numpy as jnp
        import ml_dtypes

        x_host = np.asarray(x_host)
        if x_host.ndim != 2:
            # generic [N, D] unit rows at ANY D — the tree is
            # dimension-agnostic (module docstring), so the only
            # structural requirement is rank 2, not the 2-D world of
            # the reference's grid decomposition
            raise ValueError(
                "spill device payload must be [N, D] unit rows, got "
                f"shape {x_host.shape}"
            )
        xb = np.asarray(x_host, dtype=ml_dtypes.bfloat16)
        # supervised upload: the bf16 payload is the biggest single
        # transfer of the cosine route (~1 GB at 1M x 512 over the
        # tunnel) and exactly where a flaky link faults — retry with
        # backoff before the caller degrades the run to host BLAS.
        # The span/counters below are what lets bench.py split a timed
        # rep's upload_s from its compute_s (hot vs cold resident cache)
        t0 = time.perf_counter()
        with obs.span(
            "spill.payload_upload", bytes=int(xb.nbytes), rows=int(len(xb))
        ) as sp:
            x_dev = faults.supervised(
                faults.SITE_SPILL,
                lambda _b: jnp.asarray(xb),
                label="payload-upload",
            )
            sp.sync(x_dev)
        # counted AFTER the span closes so a device-sync boundary
        # (DBSCAN_TIME_DEVICE=1) folds the blocking wait into upload_s
        obs.count("transfer.h2d_bytes", int(xb.nbytes))
        obs.count("transfer.payload_upload_bytes", int(xb.nbytes))
        obs.timed_count("transfer.payload_upload_s", t0)
        # HBM occupancy right after the biggest single allocation of
        # the cosine route lands — the watermark that says whether the
        # resident payload is what pushes a later dispatch into
        # RESOURCE_EXHAUSTED
        obs_memory.sample("spill.payload_upload")
        return cls(x_dev, x_host.shape[0], x_host.shape[1])

    def take(self, idx: np.ndarray) -> "DeviceNodeOps":
        import jax.numpy as jnp

        idx_np = np.asarray(idx, np.int32)
        # the child's upload is the index vector, not its rows —
        # exactly the transfer saving the resident design buys
        obs.count("transfer.h2d_bytes", int(idx_np.nbytes))
        idx32 = jnp.asarray(idx_np)
        with obs.span("spill.child_gather", rows=int(len(idx))):
            return DeviceNodeOps(
                faults.supervised(
                    faults.SITE_SPILL,
                    lambda _b: obs_compile.tracked_call(
                        "spill.gather", _gather_fn(), self.x, idx32
                    ),
                    label="child-gather",
                ),
                len(idx),
                self.dim,
            )


@functools.lru_cache(maxsize=1)
def _gather_fn():
    import jax

    return jax.jit(lambda x, idx: x[idx])


def _jax():
    import jax
    import jax.numpy as jnp

    return jax, jnp


def _ladder8(m: int, cap: int = 192) -> int:
    """Quantize a pivot count up the shared geometric ladder (multiple
    8, capped): device kernels are keyed on the count, and the raw
    data-dependent values would mint a fresh XLA compile per spill-tree
    node. Extra pivots are harmless — selection quality only, and the
    halo-separation filter drops any excess."""
    from dbscan_tpu.parallel.binning import _ladder_width

    return min(_ladder_width(m, 8), cap)


@functools.lru_cache(maxsize=32)
def _farthest_lloyd_fn(m: int, dim: int, cap_iters: int = 2):
    """Jitted farthest-point seeding + ``cap_iters`` Lloyd steps.

    Farthest-point is the host algorithm verbatim: start from row
    ``seed0``, repeatedly take the row maximizing the running min-chord.
    Lloyd: assign to nearest pivot (max dot), renormalized cell means.
    Returns ([m, D] f32 pivots, [m] bool valid) — empty cells invalid.
    """
    jax, jnp = _jax()

    def fn(x, seed0):
        n = x.shape[0]
        xf = x.astype(jnp.float32)

        def fp_body(i, st):
            piv, dmin = st
            j = jnp.argmax(dmin)
            row = xf[j]
            piv = piv.at[i].set(row)
            d = 2.0 - 2.0 * (xf @ row)
            dmin = jnp.minimum(dmin, jnp.maximum(d, 0.0))
            return piv, dmin

        piv0 = jnp.zeros((m, dim), jnp.float32)
        d0 = jnp.full((n,), jnp.inf, jnp.float32)
        # seed exactly like the host: first pivot is the seed row, the
        # rest follow the farthest-point recurrence
        piv0 = piv0.at[0].set(xf[seed0])
        d0 = jnp.maximum(2.0 - 2.0 * (xf @ xf[seed0]), 0.0)
        piv, _ = jax.lax.fori_loop(1, m, fp_body, (piv0, d0))

        def lloyd(_, piv):
            a = jnp.argmax(xf @ piv.T, axis=1)
            sums = jax.ops.segment_sum(xf, a, num_segments=m)
            norms = jnp.linalg.norm(sums, axis=1, keepdims=True)
            newp = sums / jnp.maximum(norms, 1e-12)
            # empty/degenerate cells keep their previous vector; the
            # host drops them — the valid mask below reproduces that
            return jnp.where(norms > 1e-12, newp, piv)

        piv = jax.lax.fori_loop(0, cap_iters, lloyd, piv)
        a = jnp.argmax(xf @ piv.T, axis=1)
        mass = jax.ops.segment_sum(
            jnp.ones((n,), jnp.int32), a, num_segments=m
        )
        return piv, mass

    return jax.jit(fn)


def pivot_vectors_device(sub: DeviceNodeOps, m: int, halo: float, rng):
    """Device counterpart of spill._pivot_vectors: farthest-point seeds
    + 2 Lloyd steps on the resident rows, then the host's greedy
    halo-separation filter on the pulled [m, D] pivots (O(m^2), host).
    Pivot choice never affects correctness (spill.py module docstring),
    so bf16 rows need no slack here."""
    if sub.n < 2:
        return np.zeros((0, sub.dim), np.float32)
    fn = _farthest_lloyd_fn(_ladder8(int(m)), int(sub.dim))
    seed0 = int(rng.integers(sub.n))
    piv, mass = fn(sub.x, seed0)
    # ONE host sync for both outputs (device_get on the pair) instead of
    # two sequential np.asarray round-trips — per NODE this is small,
    # but the tree calls this once per escalation attempt per node and
    # the tunnel charges ~latency per sync, not per byte
    import jax

    piv, mass = jax.device_get((piv, mass))
    piv = np.asarray(piv, dtype=np.float32)
    mass = np.asarray(mass)
    keep = mass > 0
    piv, mass = piv[keep], mass[keep]
    if len(piv) < 2:
        return piv
    from dbscan_tpu.parallel.spill import halo_separation_filter

    return halo_separation_filter(piv, mass, halo)


@functools.lru_cache(maxsize=32)
def _membership_fn(dim: int):
    """Jitted full-node membership pass. Returns (assign u8, member
    bits packed along the pivot axis, band-hit counts per cell, d_min).

    The band formula mirrors spill._membership exactly — intersection
    of the radius band ``r_c + halo`` and the classic ``d_min + 2*halo``
    — with ``slack`` added where the bf16 chord error could SHRINK a
    band (r from underestimated d_min, d overestimated): bands only
    grow, so the copy-set stays a superset of the host-f32 one.
    """
    jax, jnp = _jax()

    def fn(x, piv, n_valid, halo, slack):
        xf = x.astype(jnp.float32)
        d = 2.0 - 2.0 * (xf @ piv.T)
        d = jnp.sqrt(jnp.maximum(d, 0.0))
        m = d.shape[1]
        # pivots are ladder-padded so the kernel compiles once per rung,
        # not per data-dependent count; padded columns can never win
        d = jnp.where(jnp.arange(m)[None, :] < n_valid, d, jnp.inf)
        assign = jnp.argmin(d, axis=1)
        dmin = jnp.take_along_axis(d, assign[:, None], axis=1)[:, 0]
        r = jax.ops.segment_max(
            dmin, assign, num_segments=m, indices_are_sorted=False
        )
        # segment_max of an empty segment is -inf: exactly the host's
        # "cells nobody is assigned to need no copies" convention.
        # Host formula verbatim (spill._membership), each band +2*slack:
        # measured d overestimates by <= slack while measured r (or the
        # point's own d_min) underestimates by <= slack, so the true-
        # distance copy-set condition implies the inflated measured one.
        member = (d <= (r + halo + 2.0 * slack)[None, :]) & (
            d <= (dmin + 2.0 * halo + 2.0 * slack)[:, None]
        )
        sizes = member.sum(axis=0, dtype=jnp.int32)
        packed = jnp.packbits(member, axis=1)
        return assign.astype(jnp.uint8), packed, sizes, dmin

    return jax.jit(fn)


def membership_device(sub: DeviceNodeOps, piv: np.ndarray, halo: float):
    """(assign, member) for the full node, computed on device; pulls
    [n] assign bytes + packed member bits. Matches spill._membership's
    bands inflated by BF16_CHORD_SLACK (superset copy-sets)."""
    import jax.numpy as jnp

    fn = _membership_fn(int(sub.dim))
    m = piv.shape[0]
    m_pad = _ladder8(max(m, 1), cap=max(192, m))
    piv_pad = np.zeros((m_pad, piv.shape[1]), dtype=np.float32)
    piv_pad[:m] = piv
    assign, packed, sizes, _ = fn(
        sub.x,
        jnp.asarray(piv_pad),
        jnp.int32(m),
        jnp.float32(halo),
        jnp.float32(BF16_CHORD_SLACK),
    )
    member = np.unpackbits(
        np.asarray(packed), axis=1, count=m_pad
    ).astype(bool)[:, :m]
    return np.asarray(assign).astype(np.int64), member


def screen_dup_device(sub: DeviceNodeOps, piv: np.ndarray, halo: float):
    """Sampled rejection screen: (dup per point, cell count). Pulls two
    scalars. No slack — the screen only chooses whether to escalate."""
    import jax.numpy as jnp

    fn = _membership_fn(int(sub.dim))
    m = piv.shape[0]
    m_pad = _ladder8(max(m, 1), cap=max(192, m))
    piv_pad = np.zeros((m_pad, piv.shape[1]), dtype=np.float32)
    piv_pad[:m] = piv
    _, _, sizes, _ = fn(
        sub.x,
        jnp.asarray(piv_pad),
        jnp.int32(m),
        jnp.float32(halo),
        jnp.float32(0.0),
    )
    sizes = np.asarray(sizes)[:m]
    return float(sizes.sum()) / max(1, sub.n), m


_COVER_BLOCK = 512


def _make_cover(jax, jnp, dim: int, cap: int):
    """The greedy-cover loop body shared by the single-radius function
    (kept for targeted tests) and the fused escalation ladder: walk the
    permutation, every row farther than ``t`` from all leaders becomes
    one (minus slack: bf16 could OVERestimate a distance and mint a
    leader the host would skip — extra leaders are harmless, but a
    MISSED cover is not, so the coverage test uses t + slack nowhere and
    the canopy band carries the slack instead; the sequential walk
    semantics match the host exactly up to quantization/reduction
    order). BLOCKED: each while-iteration takes the first K uncovered
    candidates in perm order, resolves the in-block greedy (a candidate
    covered by an earlier in-block pick drops — identical to the
    one-at-a-time walk) with one [K, K] pairwise pass + a K-step scan,
    and updates coverage with ONE [n, K] matmul — ~L/K iterations
    instead of L (measured 5.7 s -> sub-second at L=2000, n=1M,
    D=512). Returns ``cover(xf, t) -> (buf [cap+1, D], nb, overflow)``
    over pre-permuted f32 rows."""
    K = _COVER_BLOCK

    def cover(xf, t):
        n = xf.shape[0]

        # dmin carries SQUARED chords (no per-iteration [n] sqrt);
        # coverage therefore tests against t^2 — comparing chord^2
        # against the LINEAR t would regress the cover radius to
        # sqrt(t), under-mint leaders, and void the canopy exact-cover
        # proof for data with spread in (t, sqrt(t))
        t2 = t * t

        def cond(st):
            _, nb, dmin, overflow = st
            return (~overflow) & (dmin.max() > t2)

        def body(st):
            buf, nb, dmin, _ = st
            unc = dmin > t2
            cs = jnp.cumsum(unc.astype(jnp.int32))
            kfound = jnp.minimum(cs[-1], K)
            # first K uncovered, in perm order: scatter positions into
            # their rank slot (non-selected rows dump into slot K)
            slot = jnp.where(unc & (cs <= K), cs - 1, K)
            idx = (
                jnp.zeros(K + 1, jnp.int32)
                .at[slot]
                .set(jnp.arange(n, dtype=jnp.int32), mode="drop")[:K]
            )
            rows = xf[idx]  # [K, D]; rows at rank >= kfound are junk
            validk = jnp.arange(K) < kfound
            pair2 = 2.0 - 2.0 * (rows @ rows.T)  # squared chords

            # in-block greedy, perm order: keep i iff no EARLIER kept
            # candidate covers it (exactly what the sequential walk
            # would have decided; pre-block leaders can't cover any
            # candidate — they are all measured-uncovered)
            def bstep(i, keep):
                covered = jnp.any(
                    keep
                    & (jnp.arange(K) < i)
                    & (pair2[i] <= t2)
                )
                return keep.at[i].set(validk[i] & ~covered)

            keep = jax.lax.fori_loop(
                1, K, bstep, jnp.zeros(K, bool).at[0].set(validk[0])
            )
            nkeep = keep.sum(dtype=jnp.int32)  # >= 1: progress
            kcs = jnp.cumsum(keep.astype(jnp.int32))
            dest = jnp.where(keep, nb + kcs - 1, cap)
            buf = buf.at[dest].set(rows, mode="drop")
            d2 = 2.0 - 2.0 * (xf @ rows.T)  # [n, K]
            d2 = jnp.where(keep[None, :], d2, jnp.inf)
            dmin = jnp.minimum(dmin, jnp.maximum(d2.min(axis=1), 0.0))
            return buf, nb + nkeep, dmin, nb + nkeep > cap

        buf0 = jnp.zeros((cap + 1, dim), jnp.float32)  # +1: drop slot
        d0 = jnp.full((n,), jnp.inf, jnp.float32)
        buf, nb, _, overflow = jax.lax.while_loop(
            cond, body, (buf0, jnp.int32(0), d0, jnp.bool_(False))
        )
        return buf, nb, overflow

    return cover


@functools.lru_cache(maxsize=8)
def _greedy_leaders_fn(dim: int, cap: int):
    """Jitted single-radius greedy cover (see :func:`_make_cover`);
    returns (leader rows [cap, D] f32, count, overflowed)."""
    jax, jnp = _jax()
    cover = _make_cover(jax, jnp, dim, cap)

    def fn(x, perm, t):
        xf = x.astype(jnp.float32)[perm]
        buf, nb, overflow = cover(xf, t)
        return buf[:cap], nb, overflow

    return jax.jit(fn)


#: fixed rung-ladder width of the fused cover (the escalation list is
#: at most (2, 4, 8) x halo; shorter deduped ladders pad by repeating
#: the last rung, which the `r < n_rungs` loop bound never evaluates)
_LADDER_RUNGS = 3


@functools.lru_cache(maxsize=8)
def _greedy_leaders_ladder_fn(dim: int, cap: int):
    """Jitted FUSED escalation ladder: run the greedy cover at rung
    ``ts[0]``; while it overflows the cap, rerun at the next rung — all
    on device, so the whole ladder costs ONE dispatch and ONE host sync
    instead of one per rung (each rung's overflow check was a ~0.5 s
    round-trip on the tunneled TPU). ``ts`` is the host-deduped [3]
    radius ladder (bf16 floor + the 1.9 canopy cutoff applied on the
    host, exactly the per-rung loop it replaces), ``n_rungs`` the live
    prefix length. Returns (leader rows [cap, D], count, overflowed,
    rung index used)."""
    jax, jnp = _jax()
    cover = _make_cover(jax, jnp, dim, cap)

    def fn(x, perm, ts, n_rungs):
        xf = x.astype(jnp.float32)[perm]

        def outer_cond(st):
            r, _, _, overflow = st
            return (r < n_rungs) & overflow

        def outer_body(st):
            r, _, _, _ = st
            buf, nb, overflow = cover(xf, ts[r])
            return r + jnp.int32(1), buf, nb, overflow

        buf0, nb0, ov0 = cover(xf, ts[0])
        r, buf, nb, overflow = jax.lax.while_loop(
            outer_cond, outer_body, (jnp.int32(1), buf0, nb0, ov0)
        )
        return buf[:cap], nb, overflow, r - 1

    return jax.jit(fn)


@functools.lru_cache(maxsize=32)
def _canopy_fn(dim: int):
    """Jitted canopy pass: nearest leader per point, leader-leader
    canopy-overlap adjacency (M^T M of the banded membership — a point
    in two canopies connects them; clique vs the host's star edges, same
    components), and the total membership count for the edge budget."""
    jax, jnp = _jax()

    def fn(x, leaders, n_valid, band):
        xf = x.astype(jnp.float32)
        d = 2.0 - 2.0 * (xf @ leaders.T)
        d = jnp.sqrt(jnp.maximum(d, 0.0))
        # leaders ladder-padded (one compile per rung); padded columns
        # sit at +inf so they never cover or win nearest
        lmask = jnp.arange(d.shape[1])[None, :] < n_valid
        d = jnp.where(lmask, d, jnp.inf)
        nearest = jnp.argmin(d, axis=1)
        mf = (d <= band).astype(jnp.float32)
        adj = (mf.T @ mf) > 0.0
        # per-leader counts, summed on the host in f64: a single on-
        # device f32 total loses integer precision past 2^24 and int32
        # overflows at n*L ~ 4e9; each column count <= n < 2^24 is exact
        return nearest.astype(jnp.int32), adj, mf.sum(axis=0)

    return jax.jit(fn)


def leader_components_device(
    sub: DeviceNodeOps, halo: float, rng, edge_budget: int
):
    """Device counterpart of spill.leader_components: greedy cover at
    escalating radii, canopy-overlap union, exact-cover components.
    The canopy band carries BF16_CHORD_SLACK on BOTH the cover radius
    (a true distance may exceed the measured-under-t by slack) and the
    accepted-pair halo — the cover proof's triangle inequality then
    holds for TRUE distances, so components remain exact covers."""
    from dbscan_tpu.parallel.graph import uf_components

    n = sub.n
    # ONE permutation shared by every escalation rung: the greedy walk
    # is a deterministic function of (perm, t), so the t == t_prev dedup
    # below is provably sound — a same-radius rerun with the same perm
    # must overflow identically. (Per-rung draws would make that claim
    # false: a different walk order could stay under _LEADER_CAP.)
    perm = rng.permutation(n).astype(np.int32)
    # Host-side rung ladder, exactly the per-rung loop this replaces:
    # bf16 floor on the cover radius (a covered point's MEASURED chord
    # to its leader can read as high as the slack — a self-chord under
    # bf16 is not 0 — so a minting radius below the slack could never
    # terminate; the proof only needs SOME radius, so the floor costs
    # nothing but leader density), clamped duplicates dropped, and the
    # 1.9 canopy cutoff ending the ladder.
    rungs = []
    t_prev = None
    for t_mult in (2.0, 4.0, 8.0):
        t = max(t_mult * halo, BF16_CHORD_SLACK)
        if t == t_prev:
            continue
        t_prev = t
        if t + halo >= 1.9:
            break
        rungs.append(t)
    if not rungs:
        return None
    import jax.numpy as jnp

    # The whole escalation runs FUSED on device: one dispatch, one host
    # sync for up to three rungs, instead of a blocking overflow check
    # per rung (the per-rung host round-trips were the dominant
    # fixed cost of this pass on the tunneled TPU). Pad the ladder by
    # repeating the last rung — the `r < n_rungs` bound never runs pads.
    ts = np.full(_LADDER_RUNGS, rungs[-1], dtype=np.float32)
    ts[: len(rungs)] = rungs
    fn = _greedy_leaders_ladder_fn(int(sub.dim), _LEADER_CAP)
    buf, nb, overflow, used = fn(
        sub.x, jnp.asarray(perm), jnp.asarray(ts), jnp.int32(len(rungs))
    )
    if bool(overflow):
        return None  # every rung exceeded the cap
    nb = int(nb)
    if nb < 2:
        return None
    t = float(rungs[int(used)])
    # true cover radius <= t + slack (measured <= t); both
    # endpoints of an accepted pair then MEASURE within
    # t + halo + 2*slack of the covering leader
    band = t + halo + 2.0 * BF16_CHORD_SLACK
    cfn = _canopy_fn(int(sub.dim))
    l_pad = _ladder8(nb, cap=_LEADER_CAP)
    nearest, adj, col_counts = cfn(
        sub.x,
        jnp.asarray(np.asarray(buf)[:l_pad]),
        jnp.int32(nb),
        jnp.float32(band),
    )
    total = float(
        np.asarray(col_counts, dtype=np.float64)[:nb].sum()
    )
    if total > edge_budget * n:
        return None  # canopies overlap heavily; larger radii more so
    adj = np.asarray(adj)[:nb, :nb]
    ea, eb = np.nonzero(np.triu(adj, k=1))
    n_comp, gids = uf_components(
        ea.astype(np.int64), eb.astype(np.int64), nb
    )
    if n_comp < 2:
        return None
    comp = (np.asarray(gids)[np.asarray(nearest)] - 1).astype(np.int32)
    return comp, int(n_comp)


# --- level-synchronous tree build (one dispatch per level) -------------
#
# The host recursion above — kept behind DBSCAN_SPILL_DEVICE_TREE=0 as
# the parity oracle — dispatches per NODE: pivot selection, screen,
# membership, and the child gather each cost a device round-trip, and a
# deep tree pays hundreds of them (spill_partition_s = 51/65 s sparse,
# 3.9/5.1 s cosine per BENCH_TPU_r05c). The level-synchronous build
# (Prokopenko et al., arXiv:2103.05162; Wang et al., arXiv:1912.06255)
# processes ALL open nodes of a level in ONE fused dispatch:
#
#   - the previous level's membership bits are compacted on device into
#     the new level's slot-contiguous instance layout (open nodes first,
#     then retiring leaf slots, then fallback slots — so the host's only
#     data pull is one contiguous leaf-region slice per level, submitted
#     through the PR-5 PullEngine to overlap the next level's compute);
#   - batched farthest-point seeding + 2 Lloyd steps + the greedy
#     halo-separation filter + the full-node membership pass run as
#     fori_loop/segment-reduce kernels keyed on the node-id vector, so
#     one [M] instance stream serves every open node at once;
#   - the only synchronous pull per level is the [S, m] cell-size /
#     pivot-validity table the host split policy reads.
#
# Shapes are ratcheted (instance capacity up binning._ladder_width,
# node/pivot slots up _ladder8), so the level loop re-traces only when a
# rung changes — a second same-shaped build compiles nothing (pinned by
# tests/test_spill_tree.py). The rejection screen is subsumed: the fused
# pass computes exact full-node sizes anyway, so escalation decisions
# use them directly. Nodes the pivot policy cannot split (pkeep < 2,
# attempts exhausted, concentration signature) are emitted as fallback
# items and re-enter spill.py's host-recursion stack, which owns the
# leader-cover / prefix-split / oversized-leaf ladder unchanged.

#: node slots per level dispatch (piv/pair2 temps scale with S*m*D)
_LEVEL_NODE_CAP = 512
#: instance-capacity ladder multiple for the level buffers
_LEVEL_LADDER = 1024


def _level_ladder(c: int) -> int:
    from dbscan_tpu.parallel.binning import _ladder_width

    return _ladder_width(max(1, int(c)), _LEVEL_LADDER)


def _make_level_compact(jax, jnp, mp_pad, sp_pad, mcap_p, t_pad, mcap):
    """Compaction closure: scatter the previous level's (instance, cell)
    memberships into the new slot-contiguous layout. ``dest`` maps each
    (node, cell) to its destination slot (-1 dead); a ``carry`` node
    re-emits every instance once (escalation retries, fallback
    extraction, and the fabricated root all ride this path). Ranks come
    from a per-column cumsum rebased at each node's start — node blocks
    are contiguous, so the column cumsum is per-(node, cell) exact."""

    def compact(
        idx_p, home_p, assign_p, member_p, base_p, dest, carry,
        out_base, total_p,
    ):
        pos = jnp.arange(mcap_p, dtype=jnp.int32)
        node_of = jnp.clip(
            jnp.searchsorted(base_p, pos, side="right") - 1, 0, sp_pad - 1
        ).astype(jnp.int32)
        inst_valid = pos < total_p
        memb = jnp.unpackbits(member_p, axis=1, count=mp_pad).astype(bool)
        carried = carry[node_of]
        first_col = jnp.arange(mp_pad) == 0
        memb_e = jnp.where(carried[:, None], first_col[None, :], memb)
        memb_e = memb_e & inst_valid[:, None]
        dst = dest[node_of]  # [mcap_p, mp_pad]
        live = memb_e & (dst >= 0)
        # split child j keeps home iff the instance's nearest kept cell
        # IS j (exactly one per home instance — the home-chain
        # invariant); carried nodes pass home through unchanged
        home_e = jnp.where(
            carried[:, None],
            home_p[:, None],
            home_p[:, None]
            & (assign_p[:, None] == jnp.arange(mp_pad)[None, :]),
        )
        colcs = jnp.cumsum(live.astype(jnp.int32), axis=0)  # inclusive
        node_start = jnp.maximum(base_p[:sp_pad] - 1, 0)
        col_start = jnp.where(
            (base_p[:sp_pad] > 0)[:, None], colcs[node_start], 0
        )
        rank = colcs - 1 - col_start[node_of]
        outpos = jnp.where(
            live,
            out_base[jnp.clip(dst, 0, t_pad - 1)] + rank,
            mcap,  # out of bounds: dropped by the scatter
        )
        flat = outpos.reshape(-1)
        out_idx = (
            jnp.zeros((mcap,), jnp.int32)
            .at[flat]
            .set(
                jnp.broadcast_to(
                    idx_p[:, None], (mcap_p, mp_pad)
                ).reshape(-1),
                mode="drop",
            )
        )
        out_home = (
            jnp.zeros((mcap,), bool)
            .at[flat]
            .set(home_e.reshape(-1), mode="drop")
        )
        return out_idx, out_home

    return compact


def _make_level_build(jax, jnp, dim, m_pad, s_pad, mcap, msel, matmul):
    """Build closure: one level's pivot selection + membership over all
    open nodes at once. Mirrors the host algorithms keyed by a node-id
    vector: farthest-point and Lloyd run on the COMPACTED selection
    sample (``sel_pos``, <= _PIVOT_SAMPLE rows per node — exactly the
    host's sampling split: selection cost rides the sample, the exact
    full-node membership pass rides everything); the halo-separation
    filter is the host greedy (mass-descending, drop within halo of a
    kept pivot) run rank-by-rank across every node in parallel;
    membership is spill._membership's band formula with the bf16 slack
    inflation of :func:`_membership_fn`. Pivot choice never affects
    correctness, so fp/Lloyd need no slack; the bands carry 2*slack.

    ``matmul``: compute the [rows, m] own-node pivot dots as ONE
    [rows, S*m] MXU matmul + per-row block gather (the fast shape when
    the cross product fits the level-slot budget — always true at the
    root, where S is 1); otherwise one [rows, D] pivot gather per
    pivot slot (bandwidth ~ m*rows*D, the fallback for wide levels
    whose nodes are small)."""
    sgsum = jax.ops.segment_sum
    sgmax = jax.ops.segment_max
    sgmin = jax.ops.segment_min

    def node_dots(rows, piv, node_r):
        # D[i, j] = rows[i] . piv[node_r[i], j]
        if matmul:
            g = rows @ piv.reshape(s_pad * m_pad, dim).T
            cols = node_r[:, None] * m_pad + jnp.arange(m_pad)[None, :]
            return jnp.take_along_axis(g, cols, axis=1)

        def col(j, acc):
            pj = piv[:, j, :][node_r]
            return acc.at[:, j].set(jnp.sum(rows * pj, axis=1))

        return jax.lax.fori_loop(
            0, m_pad, col,
            jnp.zeros((rows.shape[0], m_pad), jnp.float32),
        )

    def build(x, idx, home, base, sel_pos, seed_pos, m_req, total, halo,
              slack):
        del home  # home flags ride the NEXT compact, not the build
        pos = jnp.arange(mcap, dtype=jnp.int32)
        node_of = jnp.clip(
            jnp.searchsorted(base, pos, side="right") - 1, 0, s_pad - 1
        ).astype(jnp.int32)
        inst_valid = pos < total
        xr = x[idx].astype(jnp.float32)
        node_live = m_req > 0

        # compacted selection sample: fp/Lloyd touch ONLY these rows
        sel_ok = sel_pos < total
        sel_clip = jnp.clip(sel_pos, 0, mcap - 1)
        xs = xr[sel_clip]  # [msel, D]
        node_s = node_of[sel_clip]
        spos = jnp.arange(msel, dtype=jnp.int32)

        # farthest-point seeding on the sample
        p0 = xs[jnp.clip(seed_pos, 0, msel - 1)]
        p0 = jnp.where(node_live[:, None], p0, 0.0)
        piv = jnp.zeros((s_pad, m_pad, dim), jnp.float32).at[:, 0, :].set(p0)
        pvalid = jnp.zeros((s_pad, m_pad), bool).at[:, 0].set(node_live)
        g0 = piv[:, 0, :][node_s]
        d0 = jnp.maximum(2.0 - 2.0 * jnp.sum(xs * g0, axis=1), 0.0)

        def fp_body(j, st):
            piv, pvalid, dmin = st
            v = jnp.where(sel_ok, dmin, -jnp.inf)
            segtop = sgmax(v, node_s, num_segments=s_pad)
            newvalid = (segtop > 0.0) & (j < m_req)
            iswin = sel_ok & (v == segtop[node_s]) & newvalid[node_s]
            cand = jnp.where(iswin, spos, msel)
            win = sgmin(cand, node_s, num_segments=s_pad)
            rowj = xs[jnp.clip(win, 0, msel - 1)]
            rowj = jnp.where(newvalid[:, None], rowj, 0.0)
            piv = piv.at[:, j, :].set(rowj)
            pvalid = pvalid.at[:, j].set(newvalid)
            dj = jnp.maximum(
                2.0 - 2.0 * jnp.sum(xs * rowj[node_s], axis=1), 0.0
            )
            dmin = jnp.where(
                newvalid[node_s], jnp.minimum(dmin, dj), dmin
            )
            return piv, pvalid, dmin

        piv, pvalid, _ = jax.lax.fori_loop(
            1, m_pad, fp_body, (piv, pvalid, d0)
        )

        def lloyd(_, st):
            piv, pvalid = st
            dots = node_dots(xs, piv, node_s)
            dots = jnp.where(
                pvalid[node_s] & sel_ok[:, None], dots, -jnp.inf
            )
            a = jnp.argmax(dots, axis=1)
            key = node_s * m_pad + a.astype(jnp.int32)
            sums = sgsum(
                jnp.where(sel_ok[:, None], xs, 0.0),
                key,
                num_segments=s_pad * m_pad,
            )
            norms = jnp.linalg.norm(sums, axis=1, keepdims=True)
            newp = (sums / jnp.maximum(norms, 1e-12)).reshape(
                s_pad, m_pad, dim
            )
            ok = (norms[:, 0] > 1e-12).reshape(s_pad, m_pad)
            piv = jnp.where((ok & pvalid)[..., None], newp, piv)
            return piv, pvalid

        piv, pvalid = jax.lax.fori_loop(0, 2, lloyd, (piv, pvalid))

        # sample cell masses (empty cells drop, host convention)
        dots = node_dots(xs, piv, node_s)
        dots = jnp.where(pvalid[node_s] & sel_ok[:, None], dots, -jnp.inf)
        a = jnp.argmax(dots, axis=1).astype(jnp.int32)
        mass = sgsum(
            sel_ok.astype(jnp.int32),
            node_s * m_pad + a,
            num_segments=s_pad * m_pad,
        ).reshape(s_pad, m_pad)
        pvalid = pvalid & (mass > 0)

        # greedy halo-separation filter (host semantics, all nodes in
        # parallel): walk pivots in descending sample mass, drop any
        # within halo chord of a kept one
        pair2 = jnp.maximum(
            2.0 - 2.0 * jnp.einsum("sid,sjd->sij", piv, piv), 0.0
        )
        h2 = halo * halo
        order = jnp.argsort(
            jnp.where(pvalid, -mass.astype(jnp.float32), jnp.inf),
            axis=1,
            stable=True,
        )
        srange = jnp.arange(s_pad)
        keep0 = jnp.take_along_axis(pvalid, order[:, :1], 1)[:, 0]
        keepr0 = jnp.zeros((s_pad, m_pad), bool).at[:, 0].set(keep0)
        rmask = jnp.arange(m_pad)

        def hstep(r, keepr):
            cur = order[:, r]
            rowcur = pair2[srange[:, None], cur[:, None], order]
            curvalid = jnp.take_along_axis(pvalid, cur[:, None], 1)[:, 0]
            covered = jnp.any(
                keepr & (rmask < r)[None, :] & (rowcur <= h2), axis=1
            )
            return keepr.at[:, r].set(curvalid & ~covered)

        keepr = jax.lax.fori_loop(1, m_pad, hstep, keepr0)
        pkeep = (
            jnp.zeros((s_pad, m_pad), bool)
            .at[srange[:, None], order]
            .set(keepr)
        )

        # full-node membership over the kept pivots (band formula of
        # spill._membership, +2*slack per band as in _membership_fn)
        dots = node_dots(xr, piv, node_of)
        dchord = jnp.sqrt(jnp.maximum(2.0 - 2.0 * dots, 0.0))
        dchord = jnp.where(pkeep[node_of], dchord, jnp.inf)
        assign = jnp.argmin(dchord, axis=1).astype(jnp.int32)
        dminc = jnp.take_along_axis(dchord, assign[:, None], 1)[:, 0]
        r_c = sgmax(
            jnp.where(inst_valid, dminc, -jnp.inf),
            node_of * m_pad + assign,
            num_segments=s_pad * m_pad,
        ).reshape(s_pad, m_pad)
        member = (dchord <= r_c[node_of] + (halo + 2.0 * slack)) & (
            dchord <= (dminc + 2.0 * halo + 2.0 * slack)[:, None]
        )
        member = member & inst_valid[:, None] & pkeep[node_of]
        sizes = sgsum(
            member.astype(jnp.int32), node_of, num_segments=s_pad
        )
        packed = jnp.packbits(member, axis=1)
        return packed, assign, sizes, pkeep

    return build


@functools.lru_cache(maxsize=64)
def _level_step_fn(dim, mp_pad, sp_pad, mcap_p, t_pad, m_pad, s_pad,
                   mcap, msel, matmul):
    """ONE fused level dispatch: compact the previous level's membership
    into the new layout, then build pivots/membership for its open
    prefix. The root level rides the same signature with a fabricated
    single-carry previous level, so the whole tree uses one compiled
    family (``spill.level``)."""
    jax, jnp = _jax()
    compact = _make_level_compact(jax, jnp, mp_pad, sp_pad, mcap_p, t_pad, mcap)
    build = _make_level_build(jax, jnp, dim, m_pad, s_pad, mcap, msel, matmul)

    def fn(
        x, idx_p, home_p, assign_p, member_p, base_p, dest, carry,
        out_base, sel_pos, seed_pos, m_req, base, total_p, total, halo,
        slack,
    ):
        idx, home = compact(
            idx_p, home_p, assign_p, member_p, base_p, dest, carry,
            out_base, total_p,
        )
        packed, assign, sizes, pkeep = build(
            x, idx, home, base, sel_pos, seed_pos, m_req, total, halo,
            slack,
        )
        return idx, home, packed, assign, sizes, pkeep

    return jax.jit(fn)


@functools.lru_cache(maxsize=64)
def _level_final_fn(mp_pad, sp_pad, mcap_p, t_pad, mcap):
    """Closing compact-only dispatch: the last level's children are all
    leaves/fallbacks, so only the layout scatter remains."""
    jax, jnp = _jax()
    compact = _make_level_compact(jax, jnp, mp_pad, sp_pad, mcap_p, t_pad, mcap)

    def fn(idx_p, home_p, assign_p, member_p, base_p, dest, carry,
           out_base, total_p):
        return compact(
            idx_p, home_p, assign_p, member_p, base_p, dest, carry,
            out_base, total_p,
        )

    return jax.jit(fn)


def _level_m_req(count: int, attempt: int, maxpp: int) -> int:
    """Per-node pivot request: delegates to the ONE escalation formula
    (spill.pivot_escalation) the host recursion also uses, so the two
    builds cannot drift apart."""
    from dbscan_tpu.parallel import spill as _spill

    return _spill.pivot_escalation(count, attempt, maxpp)


class _LevelNode:
    """Host bookkeeping for one open node slot."""

    __slots__ = ("count", "attempt")

    def __init__(self, count: int, attempt: int = 0):
        self.count = count
        self.attempt = attempt


def build_level_tree(dev: DeviceNodeOps, n: int, maxpp: int, halo: float,
                     rng, info: dict = None):
    """Level-synchronous device build over the resident rows.

    Returns ``(leaves, fallback)``: lists of ``(row_idx, home_flag)``
    host arrays. ``leaves`` are finished spill leaves; ``fallback``
    items re-enter the host recursion (spill.py's stack), which owns
    the leader-cover / prefix-split / oversized-leaf ladder. ``info``
    (optional dict) receives ``levels`` / ``level_dispatches``.

    Split policy per node (the host recursion's, from exact full-node
    sizes): accept when duplication <= MAX_DUP_FACTOR and no child
    holds > MAX_CHILD_FRAC of the parent; otherwise escalate the pivot
    count (<= 3 attempts) unless the concentration signature (dup both
    >> the budget and ~half the kept-pivot count) says escalation
    cannot help — then fall back. Host and device trees may pick
    DIFFERENT pivots (different sampling, batched fp): the coverage
    contract plus the canonical merge ids make the final labels
    identical anyway (PARITY.md "Spill tree")."""
    import jax

    from dbscan_tpu import config
    from dbscan_tpu.parallel import pipeline as pipe_mod
    from dbscan_tpu.parallel import spill as _spill

    jnp = _jax()[1]
    slot_budget = max(1 << 20, int(config.env("DBSCAN_SPILL_LEVEL_SLOTS")))
    leaves: list = []
    fallback: list = []
    engine = pipe_mod.get_engine()
    pull_jobs: list = []

    dispatches = 0
    levels = 0

    def _supervised_call(fn_label, fn, *args):
        nonlocal dispatches
        dispatches += 1
        obs.count("spill.level_dispatches")
        return faults.supervised(
            faults.SITE_SPILL_LEVEL,
            lambda _b: obs_compile.tracked_call(fn_label, fn, *args),
            label=fn_label,
        )

    def _pull_region(idx_dev, home_dev, lo, entries, sink_of):
        """Pull one contiguous retiring region (leaf + fallback slots)
        and split it into per-slot (rows, home) pairs. ``entries`` =
        [(count, sink_name), ...] in slot order. Submitted through the
        pull engine when live, so the D2H + split overlap the next
        level's device compute."""
        if not entries:
            return
        hi = lo + sum(c for c, _ in entries)
        i_slice = idx_dev[lo:hi]
        h_slice = home_dev[lo:hi]

        def work():
            with obs.span("spill.leaf_pull", rows=int(hi - lo)):
                li, lh = jax.device_get((i_slice, h_slice))
            li = np.asarray(li, dtype=np.int64)
            lh = np.asarray(lh, dtype=bool)
            obs.count("transfer.d2h_bytes", int(li.nbytes + lh.nbytes))
            off = 0
            for cnt, sink in entries:
                sink_of[sink].append((li[off : off + cnt], lh[off : off + cnt]))
                off += cnt

        if engine is not None:
            pull_jobs.append((engine.submit(work, label="spill-leaves"), work))
        else:
            work()

    sink_of = {"leaf": leaves, "fallback": fallback}

    # fabricated previous level: one carried node holding [0, n) — the
    # root build then rides the same fused step as every later level
    mcap_p = _level_ladder(n)
    sp_pad = _ladder8(1, cap=_LEVEL_NODE_CAP)
    mp_pad = 8
    idx_p = jnp.minimum(jnp.arange(mcap_p, dtype=jnp.int32), max(0, n - 1))
    home_p = jnp.arange(mcap_p) < n
    assign_p = jnp.zeros((mcap_p,), jnp.int32)
    member_p = jnp.zeros((mcap_p, 1), jnp.uint8)
    base_p = np.zeros(sp_pad + 1, np.int32)
    base_p[1:] = n
    dest = np.full((sp_pad, mp_pad), -1, np.int32)
    dest[0, 0] = 0
    carry = np.zeros(sp_pad, bool)
    carry[0] = True
    total_p = n

    nodes = [_LevelNode(n)]
    out_base_np = np.zeros(1, np.int64)  # open slot 0 starts at 0
    retire_entries: list = []  # [(count, sink)] after the open region
    total_out = n

    try:
        while nodes:
            levels += 1
            obs.count("spill.levels")
            # node slots ride a power-of-2 ladder (not _ladder8's floor of
            # 8): the root level has ONE node, and the matmul dots path
            # scales with s_pad * m_pad columns
            s_pad = max(1, 1 << (len(nodes) - 1).bit_length())
            mcap = _level_ladder(total_out)
            # pivot-slot rung: per-node requests capped so the [M, m]
            # working set stays under the level-slot budget
            m_reqs = [
                _level_m_req(nd.count, nd.attempt, maxpp) for nd in nodes
            ]
            m_pad = _ladder8(max(m_reqs), cap=_spill._MAX_PIVOTS)
            while m_pad > 8 and mcap * m_pad > slot_budget:
                m_pad = max(8, (m_pad // 2) // 8 * 8)
            m_req = np.zeros(s_pad, np.int32)
            m_req[: len(nodes)] = np.minimum(m_reqs, m_pad)
            # the own-node dots: one [M, S*m] matmul when the cross product
            # fits the budget (always at the root), else per-slot gathers
            matmul = mcap * s_pad * m_pad <= slot_budget
            # layout of THIS level: open nodes occupy [out_base[s],
            # out_base[s] + count); the selection sample and per-node seeds
            # are node-major positions into that layout
            base = np.zeros(s_pad + 1, np.int32)
            counts = np.array([nd.count for nd in nodes], dtype=np.int64)
            starts = out_base_np[: len(nodes)]
            base[: len(nodes)] = starts
            base[len(nodes) :] = int(starts[-1] + counts[-1]) if len(nodes) else 0
            total = int(base[len(nodes)])
            sel_l = []
            seed_pos = np.zeros(s_pad, np.int32)
            for s, nd in enumerate(nodes):
                lo = int(starts[s])
                if nd.count > _spill._PIVOT_SAMPLE:
                    picks = lo + rng.choice(
                        nd.count, _spill._PIVOT_SAMPLE, replace=False
                    )
                    picks.sort()
                else:
                    picks = np.arange(lo, lo + nd.count)
                seed_pos[s] = sum(len(p) for p in sel_l) + int(
                    rng.integers(len(picks))
                )
                sel_l.append(picks)
            n_sel = sum(len(p) for p in sel_l)
            msel = _level_ladder(n_sel)
            sel_pos = np.full(msel, mcap, np.int32)  # pad: fails sel_ok
            sel_pos[:n_sel] = np.concatenate(sel_l)

            t_pad = max(8, _ladder8(len(out_base_np) + len(retire_entries), cap=1 << 20))
            out_base = np.zeros(t_pad, np.int32)
            out_base[: len(out_base_np)] = out_base_np
            off = total
            for k, (cnt, _sink) in enumerate(retire_entries):
                out_base[len(out_base_np) + k] = off
                off += cnt

            with obs.span(
                "spill.level",
                level=int(levels),
                nodes=int(len(nodes)),
                instances=int(total),
                m=int(m_pad),
            ):
                fn = _level_step_fn(
                    int(dev.dim), int(mp_pad), int(sp_pad), int(mcap_p),
                    int(t_pad), int(m_pad), int(s_pad), int(mcap),
                    int(msel), bool(matmul),
                )
                out = _supervised_call(
                    "spill.level", fn,
                    dev.x, idx_p, home_p, assign_p, member_p,
                    jnp.asarray(base_p), jnp.asarray(dest), jnp.asarray(carry),
                    jnp.asarray(out_base), jnp.asarray(sel_pos),
                    jnp.asarray(seed_pos), jnp.asarray(m_req),
                    jnp.asarray(base), int(total_p), int(total),
                    float(halo), float(BF16_CHORD_SLACK),
                )
                idx_dev, home_dev, packed_dev, assign_dev, sizes_dev, pkeep_dev = out
                # retiring region of THIS layout: pull it while the sizes
                # sync (and the next level's dispatch) proceed
                _pull_region(idx_dev, home_dev, total, retire_entries, sink_of)
                sizes, pkeep = jax.device_get((sizes_dev, pkeep_dev))
            sizes = np.asarray(sizes)
            pkeep = np.asarray(pkeep)

            # host split policy over the pulled [S, m] tables
            next_nodes: list = []
            next_starts: list = []
            next_retire: list = []  # (count, sink)
            dest2 = np.full((s_pad, m_pad), -1, np.int32)
            carry2 = np.zeros(s_pad, bool)
            open_off = 0
            retire_list: list = []  # (s-or-(s,j), count, sink) in slot order
            for s, nd in enumerate(nodes):
                cnt = nd.count
                kp = int(pkeep[s].sum())
                sz = sizes[s]
                tot = int(sz.sum())
                dup = tot / cnt
                frac = float(sz.max()) / cnt if cnt else 0.0
                split_ok = (
                    kp >= 2
                    and dup <= _spill.MAX_DUP_FACTOR
                    and frac <= _spill.MAX_CHILD_FRAC
                )
                if split_ok:
                    for j in np.flatnonzero(sz > 0):
                        cj = int(sz[j])
                        if cj <= maxpp:
                            retire_list.append((("cell", s, int(j)), cj, "leaf"))
                        elif len(next_nodes) >= _LEVEL_NODE_CAP:
                            # node-slot budget for the next dispatch: the
                            # overflow children finish on the host-recursion
                            # ladder instead (correctness unchanged; only
                            # reachable at extreme tree arity)
                            retire_list.append(
                                (("cell", s, int(j)), cj, "fallback")
                            )
                        else:
                            dest2[s, j] = len(next_nodes)
                            next_nodes.append(_LevelNode(cj))
                            next_starts.append(open_off)
                            open_off += cj
                    continue
                # escalation / fallback: the whole node carries forward
                concentration = (
                    kp >= 2
                    and dup > _spill.SCREEN_DUP_MARGIN * _spill.MAX_DUP_FACTOR
                    and dup >= _spill.CONCENTRATION_CELL_FRAC * kp
                )
                nd.attempt += 1
                if (
                    kp < 2
                    or concentration
                    or nd.attempt >= 3
                    or len(next_nodes) >= _LEVEL_NODE_CAP
                ):
                    carry2[s] = True
                    retire_list.append((("node", s), cnt, "fallback"))
                else:
                    carry2[s] = True
                    dest2[s, 0] = len(next_nodes)
                    next_nodes.append(_LevelNode(cnt, attempt=nd.attempt))
                    next_starts.append(open_off)
                    open_off += cnt
            # assign retiring slots after the open region, in list order
            for k, (tag, cnt, sink) in enumerate(retire_list):
                slot = len(next_nodes) + k
                if tag[0] == "cell":
                    _c, s, j = tag
                    dest2[s, j] = slot
                else:
                    dest2[tag[1], 0] = slot
                next_retire.append((cnt, sink))

            total_out2 = open_off + sum(c for c, _ in next_retire)

            if not next_nodes:
                # closing compact: only the layout scatter remains
                mcap2 = _level_ladder(max(1, total_out2))
                t_pad2 = max(
                    8, _ladder8(max(1, len(next_retire)), cap=1 << 20)
                )
                ob2 = np.zeros(t_pad2, np.int32)
                off = 0
                for k, (cnt, _sink) in enumerate(next_retire):
                    ob2[k] = off
                    off += cnt
                # remap dest slot ids: no open slots, so retiring slots
                # start at 0
                d2 = np.where(dest2 >= len(next_nodes), dest2 - len(next_nodes), -1)
                ffn = _level_final_fn(
                    int(m_pad), int(s_pad), int(mcap), int(t_pad2), int(mcap2)
                )
                fidx, fhome = _supervised_call(
                    "spill.level_final", ffn,
                    idx_dev, home_dev, assign_dev, packed_dev,
                    jnp.asarray(base), jnp.asarray(d2.astype(np.int32)),
                    jnp.asarray(carry2), jnp.asarray(ob2), int(total),
                )
                _pull_region(fidx, fhome, 0, next_retire, sink_of)
                break

            # roll the level state forward: this level's arrays become the
            # next step's "previous level"
            idx_p, home_p, assign_p, member_p = (
                idx_dev, home_dev, assign_dev, packed_dev,
            )
            mcap_p, sp_pad, mp_pad = mcap, s_pad, m_pad
            base_p, dest, carry, total_p = base, dest2, carry2, total
            nodes = next_nodes
            out_base_np = np.asarray(next_starts, dtype=np.int64)
            retire_entries = next_retire
            total_out = total_out2

    except BaseException:
        # a failing level dispatch degrades the WHOLE build to the
        # host recursion (spill.py's handler) — but leaf pulls
        # already submitted would keep running as orphans on the
        # shared process-wide pull worker: their spans/byte counters
        # would charge a run whose results are discarded, a pull
        # fault would be banked on a job nobody ever waits on, and
        # the ordered single worker would delay the degraded run's
        # later pipelined pulls behind them. Drain them here; their
        # results land in lists this frame is about to drop, and a
        # pull error is deliberately consumed (the build is already
        # failing with the primary exception).
        for job, _work in pull_jobs:
            try:
                engine.wait(job)
            except Exception:  # noqa: BLE001 — already degrading
                pass
        raise
    for job, work in pull_jobs:
        engine.settle(job, work)
    if info is not None:
        info["levels"] = levels
        info["level_dispatches"] = dispatches
    return leaves, fallback


def device_available() -> bool:
    """True when a non-CPU jax backend is initialized/initializable —
    the gate the spill tree uses before routing passes here. Import
    errors and dead backends degrade to the host path silently."""
    try:
        import jax

        return jax.default_backend() != "cpu"
    except Exception:  # noqa: BLE001 — any failure means "no device"
        return False
