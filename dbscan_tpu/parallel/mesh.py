"""Device mesh helpers.

The reference's execution fabric is the Spark RDD runtime (groupByKey fan-out
over executor JVMs, DBSCAN.scala:150-154); ours is a 1-D `jax.sharding.Mesh`
over the partition axis — each device processes a contiguous slab of spatial
partitions via shard_map, with ICI carrying any cross-device layout moves.
Multi-host (DCN) extends the same mesh via jax.distributed initialization.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

PARTS_AXIS = "parts"


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over all (or the given) devices, axis name 'parts'."""
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devices), (PARTS_AXIS,))


def mesh_size(mesh: Optional[Mesh]) -> int:
    if mesh is None:
        return 1
    return int(np.prod(mesh.devices.shape))
