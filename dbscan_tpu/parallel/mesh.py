"""Device mesh helpers.

The reference's execution fabric is the Spark RDD runtime (groupByKey fan-out
over executor JVMs, DBSCAN.scala:150-154); ours is a 1-D `jax.sharding.Mesh`
over the partition axis — each device processes a contiguous slab of spatial
partitions via shard_map, with ICI carrying any cross-device layout moves.
Multi-host (DCN) extends the same mesh via jax.distributed initialization.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

PARTS_AXIS = "parts"


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over all (or the given) devices, axis name 'parts'."""
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devices), (PARTS_AXIS,))


def mesh_size(mesh: Optional[Mesh]) -> int:
    if mesh is None:
        return 1
    return int(np.prod(mesh.devices.shape))


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> Mesh:
    """Join a multi-host (DCN) job and return the global partition mesh.

    The reference scales out by adding Spark executors over its cluster
    manager; the TPU equivalent is one process per host joined through
    ``jax.distributed.initialize`` (args auto-detected on TPU pods, explicit
    for manual launches), after which ``jax.devices()`` spans every host and
    the same 1-D 'parts' mesh covers the whole slice — shard_map then runs
    each host's slab locally with collectives riding ICI within a slice and
    DCN across slices. Call once per process before any other JAX API.
    """
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
    return make_mesh()
