"""Device mesh helpers.

The reference's execution fabric is the Spark RDD runtime (groupByKey fan-out
over executor JVMs, DBSCAN.scala:150-154); ours is a 1-D `jax.sharding.Mesh`
over the partition axis — each device processes a contiguous slab of spatial
partitions via shard_map, with ICI carrying any cross-device layout moves.
Multi-host (DCN) extends the same mesh via jax.distributed initialization.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from dbscan_tpu import obs

PARTS_AXIS = "parts"
#: second mesh axis of the 2-D scale-out layout (make_mesh2d): the
#: partition axis shards over BOTH axes in contiguous blocks — chip
#: (i, j) owns block i*cols+j — and the collective halo-merge
#: (parallel/halo.py) runs its psum-style neighbor exchanges along each
#: axis in turn (dimension-ordered, the torus-friendly schedule).
HALO_AXIS = "halo"


def multiprocess() -> bool:
    """True when this JAX runtime spans multiple processes (DCN job)."""
    return jax.process_count() > 1


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-portable ``shard_map``: every mesh kernel in the package
    builds through here. Newer jax exposes ``jax.shard_map`` with the
    vma (varying-mesh-axes) type discipline; 0.4.x keeps it under
    ``jax.experimental.shard_map`` with the older ``check_rep`` checker,
    which has no replication rule for ``lax.while_loop`` at all — so on
    that line the check is disabled outright (the vma discipline is a
    new-jax static check; disabling it never changes computed values).
    Without this shim every on-mesh path AttributeErrors on 0.4.x,
    which is exactly the class of environment this CPU container is."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def pvary(x, axes):
    """Version-portable ``lax.pcast(..., to="varying")``: mark a
    replicated value device-varying over ``axes`` inside a shard_map
    body (the scan-carry discipline of jax >= 0.9). Older jax has no
    varying-type system, so the no-op is exact there."""
    if not axes:
        return x
    lax = jax.lax
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes if len(axes) > 1 else axes[0], to="varying")
    return x


def shard_host_array(mesh: Optional[Mesh], x):
    """Host (numpy) array -> device input for a partition-sharded jit.

    Single-process: return the array unchanged (jit device-puts it; this
    is the zero-overhead path every existing call rides). Multi-process:
    a numpy array cannot feed a jit whose sharding spans non-addressable
    devices, so build a global jax.Array — every process packs the SAME
    full array deterministically, and each contributes exactly its
    addressable shards via the callback (the slice is taken from the
    replicated host copy, so no cross-host data movement happens here).
    This is the Spark-executor data plane inverted: instead of the driver
    shipping partitions to executors, every host derives the global
    layout and keeps only its slice on its devices.
    """
    if mesh is None or not multiprocess():
        return x
    sharding = NamedSharding(mesh, parts_spec(mesh))
    return jax.make_array_from_callback(x.shape, sharding, lambda idx: x[idx])


def replicate_host_array(x):
    """Host array -> replicated jit input.

    Multi-process: return the numpy array UNCHANGED — every process
    passes the identical (deterministically derived) value and jit
    treats it as replicated; a jnp.asarray here would commit it to one
    process's local device and clash with global-array co-inputs.
    Single-process: jnp.asarray, which starts the host->device transfer
    early (the existing async-dispatch behavior).
    """
    if multiprocess():
        return x
    import jax.numpy as jnp

    return jnp.asarray(x)


def pull_to_host(x) -> np.ndarray:
    """Device output -> full numpy array on EVERY host.

    Single-process: plain np.asarray (the existing pull path, including
    donated/committed arrays). Multi-process: shards of a global array
    are only locally addressable, so gather them across hosts first
    (DCN allgather via multihost_utils) — the host-side phases (cell-CC,
    merge) run replicated on every process, which keeps them
    deterministic and identical to the single-process result.
    """
    if isinstance(x, np.ndarray):
        return x  # already host-side: no transfer to account
    # routed through the obs HOOKS (not the registry directly) so the
    # accounting lands in whichever destination is live: the full obs
    # registries, or the always-on flight ring — a postmortem that
    # cannot say how many bytes moved before the death is half blind
    live = obs.state() is not None or obs.flight._state is not None
    t0 = time.perf_counter() if live else 0.0
    if not multiprocess() or getattr(x, "is_fully_addressable", True):
        arr = np.asarray(x)
    else:
        from jax.experimental import multihost_utils

        arr = np.asarray(multihost_utils.process_allgather(x, tiled=True))
    if live:
        # the measured wall includes any device wait np.asarray blocked
        # on (async dispatch retires here), not pure link time — that
        # is exactly the "pull" cost the driver's timings charge too
        t1 = time.perf_counter()
        obs.count("transfer.d2h_bytes", int(arr.nbytes))
        obs.count("transfer.d2h_s", t1 - t0)
        obs.add_span("transfer.pull", t0, t1, bytes=int(arr.nbytes))
    return arr


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over all (or the given) devices, axis name 'parts'."""
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devices), (PARTS_AXIS,))


def make_mesh2d(
    devices: Optional[Sequence] = None,
    shape: Optional[Tuple[int, int]] = None,
) -> Mesh:
    """2-D ('parts', 'halo') mesh: the executor grid of the reference's
    cluster mapped onto a chip torus. The partition axis shards over
    BOTH axes (parts_spec), so dispatch semantics are identical to the
    1-D mesh at the same device count; what the second axis buys is the
    dimension-ordered halo-merge exchange (parallel/halo.py) — each
    psum-style reduction runs along one torus axis at a time, the
    ICI-friendly schedule on real 2-D slices.

    ``shape``: (parts, halo) factorization of the device count; default
    honors ``DBSCAN_MESH_SHAPE`` ('PARTSxHALO', e.g. ``4x2``) and falls
    back to the most-square one (8 -> 4x2, 4 -> 2x2, 2 -> 2x1). A shape
    whose product mismatches the device count raises.
    """
    devices = list(devices) if devices is not None else jax.devices()
    k = len(devices)
    if shape is None:
        from dbscan_tpu import config

        raw = config.env("DBSCAN_MESH_SHAPE")
        if raw:
            r, _, c = str(raw).lower().partition("x")
            shape = (int(r), int(c))
    if shape is None:
        c = int(np.sqrt(k))
        while c > 1 and k % c:
            c -= 1
        shape = (k // max(1, c), max(1, c))
    if int(shape[0]) * int(shape[1]) != k:
        raise ValueError(
            f"mesh shape {tuple(shape)} does not cover {k} devices"
        )
    arr = np.array(devices).reshape(int(shape[0]), int(shape[1]))
    return Mesh(arr, (PARTS_AXIS, HALO_AXIS))


def parts_axes(mesh: Optional[Mesh]) -> tuple:
    """The mesh axis names the partition axis shards over — ('parts',)
    on the 1-D mesh, ('parts', 'halo') on the 2-D one. The tuple is
    what collectives over "all chips" (ncore psum, halo pmin rings)
    reduce over."""
    if mesh is None:
        return ()
    return tuple(mesh.axis_names)


def parts_spec(mesh: Optional[Mesh]) -> PartitionSpec:
    """PartitionSpec sharding a leading partition axis over EVERY mesh
    axis in contiguous blocks (the eps-halo'd block ownership of the
    scale-out contract, PARITY.md "Mesh scale-out")."""
    if mesh is None:
        return PartitionSpec()
    names = tuple(mesh.axis_names)
    return PartitionSpec(names if len(names) > 1 else names[0])


def mesh_size(mesh: Optional[Mesh]) -> int:
    if mesh is None:
        return 1
    return int(np.prod(mesh.devices.shape))


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> Mesh:
    """Join a multi-host (DCN) job and return the global partition mesh.

    The reference scales out by adding Spark executors over its cluster
    manager; the TPU equivalent is one process per host joined through
    ``jax.distributed.initialize`` (args auto-detected on TPU pods, explicit
    for manual launches), after which ``jax.devices()`` spans every host and
    the same 1-D 'parts' mesh covers the whole slice — shard_map then runs
    each host's slab locally with collectives riding ICI within a slice and
    DCN across slices. Call once per process before any other JAX API.
    """
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
    return make_mesh()
