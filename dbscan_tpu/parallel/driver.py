"""Distributed DBSCAN driver: host orchestration + sharded device fan-out.

The full pipeline of reference DBSCAN.scala:72-285, restructured for TPU:

| reference stage (Spark)                        | here                          |
|------------------------------------------------|-------------------------------|
| cell histogram via aggregateByKey (:91-97)     | vectorized host numpy         |
| EvenSplitPartitioner on driver (:105-106)      | integer-domain partitioner    |
| margins broadcast (:116-126)                   | [P, 4] arrays, no broadcast   |
| halo duplication flatMap (:132-137)            | vectorized containment        |
| groupByKey + per-partition LocalDBSCAN         | static [P, B] buckets +       |
|   (:150-154)                                   |   shard_map over 'parts' mesh |
| merge-candidate routing (:161-173)             | band membership, host         |
| findAdjacencies + DBSCANGraph (:179-228)       | union-find over doubly-       |
|                                                |   labeled halo points         |
| relabel inner/outer (:232-270)                 | vectorized gather + dedup     |

Known deliberate divergences from the reference (documented, all quirk
fixes):
- the reference collects the whole dataset to the driver twice for debug
  prints (DBSCAN.scala:139, :202) — not reproduced;
- a point lying exactly on a shared main-rectangle edge is emitted twice by
  the reference (once per band group); we dedup globally by point identity;
- on halo points labeled non-noise by several partitions the reference keeps
  whichever instance arrived last (:257-267); we prefer Core > Border
  deterministically (the global cluster id is identical either way — the
  instances were just unioned);
- global cluster numbering follows a deterministic order (partition id, then
  local id) instead of Spark's distinct().collect() arrival order; numbering
  is permutation-equivalent.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import hashlib
import logging
import os as _os
import time
import weakref
from typing import Callable, FrozenSet, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec

from dbscan_tpu import _native, faults, obs
from dbscan_tpu import config as config_mod
from dbscan_tpu.config import DBSCANConfig
from dbscan_tpu.lint import tsan as _tsan
from dbscan_tpu.obs import compile as obs_compile
from dbscan_tpu.obs import flight as obs_flight
from dbscan_tpu.obs import memory as obs_memory
from dbscan_tpu.ops import geometry as geo
from dbscan_tpu.ops.labels import CORE, NOISE, SEED_NONE
from dbscan_tpu.ops.local_dbscan import local_dbscan
from dbscan_tpu.parallel import binning, cellgraph, partitioner
from dbscan_tpu.parallel import mesh as mesh_mod
from dbscan_tpu.parallel import pipeline as pipe_mod
from dbscan_tpu.parallel.graph import uf_components
from dbscan_tpu.parallel.mesh import mesh_size

logger = logging.getLogger(__name__)

# Slot budget per compact-postpass chunk. Two constraints meet here:
# any single device buffer must stay under 2^31 bytes (TPU runtime
# per-buffer limit; the int32 bits array is 4 bytes/slot -> hard cap
# 2^29 slots), and the chunk is ALSO the checkpoint/restart granularity
# of the resumable device phase — a 100M-point run holds ~270M slots,
# so a near-limit budget would put the whole run in one chunk and a
# worker death would save nothing. 2^26 slots (~256 MB of bits) keeps
# several restart points per big run for a few extra ~10 s pulls.
# Env-overridable: retry loops on a dying worker shrink it further so
# partial progress lands earlier. Clamped to [2^16, 2^28]: at 2^29
# slots the int32 bits array alone reaches 2^31 bytes — AT the
# per-buffer ceiling, the exact kill the chunking exists to prevent —
# so the cap sits one doubling below it; and the value tags saved
# chunks, so one bad override would also invalidate every prior
# checkpoint of the run.
_requested_chunk_slots = int(config_mod.env("DBSCAN_COMPACT_CHUNK_SLOTS"))
_COMPACT_CHUNK_SLOTS = min(1 << 28, max(1 << 16, _requested_chunk_slots))
if _COMPACT_CHUNK_SLOTS != _requested_chunk_slots:
    # chunks are budget-stamped, so an altered value is also a clean
    # recompute of any prior checkpoints — say so instead of silently
    # discarding them
    logger.warning(
        "DBSCAN_COMPACT_CHUNK_SLOTS=%d clamped to %d (allowed range "
        "2^16..2^28); saved chunks stamped with the requested value "
        "will not be resumed",
        _requested_chunk_slots,
        _COMPACT_CHUNK_SLOTS,
    )
# Dispatched-but-unretired slot budget (dispatch backpressure): queued
# programs pin ~25 B of input per padded slot in HBM; 2^27 slots keeps
# the input window ~3 GB, leaving room for the resident phase-1 outputs
# (5 B/slot across ALL groups) and postpass transients on a 16 GB chip.
# Env-overridable for debugging (1 = fully synchronous dispatch).
# Device faults no longer abort the run at whichever site happens to
# observe them: every dispatch runs under faults.supervised (bounded
# retry/backoff, per-group CPU degradation), and a retries-exhausted
# fault flushes the current compact chunk before raising, so even the
# abort path resumes from the last completed group.
_INFLIGHT_SLOTS = int(config_mod.env("DBSCAN_INFLIGHT_SLOTS"))
_IMPORT_INFLIGHT_SLOTS = _INFLIGHT_SLOTS


def _live_chunk_slots() -> int:
    """Per-run resolution of DBSCAN_COMPACT_CHUNK_SLOTS. The module
    attribute stays the latch (and the tests' monkeypatch surface),
    but the autotuner and an applied config.Profile set knobs
    IN-PROCESS after this module imported — when the live env/profile
    value moved from the import-time read, it wins (same clamp)."""
    req = int(config_mod.env("DBSCAN_COMPACT_CHUNK_SLOTS"))
    if req == _requested_chunk_slots:
        return _COMPACT_CHUNK_SLOTS
    return min(1 << 28, max(1 << 16, req))


def _live_inflight_slots() -> int:
    """Per-run resolution of DBSCAN_INFLIGHT_SLOTS (same contract as
    :func:`_live_chunk_slots`)."""
    req = int(config_mod.env("DBSCAN_INFLIGHT_SLOTS"))
    if req == _IMPORT_INFLIGHT_SLOTS:
        return _INFLIGHT_SLOTS
    return req

# Widest bucket the dense engine may materialize
# (binning.DENSE_MAX_BUCKET — NOT the spatial routing threshold, which is
# the deliberately lower binning.BANDED_ROUTE_BUCKET): a [B, B] f32 measure matrix
# no longer fits a v5e chip's HBM at B = 65536 (17 GiB), and euclidean
# workloads at or past that width route to the banded engine instead. So a
# dense bucket REACHING this width means a path with no spatial
# decomposition (cosine / user metrics) or a force-dense expert run that is
# about to OOM the device after minutes of host packing — fail fast instead.
DENSE_WIDTH_LIMIT = binning.DENSE_MAX_BUCKET


def _check_dense_width(b: int, n: int) -> None:
    """Fail fast (clear ValueError, before any packing or device work) when
    a dense-engine bucket would materialize an unpayable [B, B] adjacency —
    the guard VERDICT r1 asked for. ``n`` is the real point count behind
    the bucket (for the diagnostic); ``b`` the padded bucket width."""
    if b < DENSE_WIDTH_LIMIT:
        return
    gib = b * b * 4 / 2**30
    raise ValueError(
        f"this configuration needs a dense [{b}, {b}] f32 pairwise-measure "
        f"matrix (~{gib:.0f} GiB) for a partition holding {n} points — at "
        f"or over the dense-engine width limit of {DENSE_WIDTH_LIMIT} "
        "slots (a 17 GiB matrix does not fit a single chip's HBM). The "
        "dense kernel is the only engine for partitions this wide. "
        "Euclidean and haversine decompose spatially and scale via the "
        "banded engine; cosine decomposes via metric spill partitioning "
        "— reaching this guard under cosine means the data could not be "
        "split (nearly everything within ~one eps-ball: raise the "
        "resolution by lowering eps, or subsample). For other metrics: "
        "lower max_points_per_partition where a decomposition exists, or "
        "subsample/pre-partition the data so each train() call stays "
        f"under {DENSE_WIDTH_LIMIT} points per partition"
    )


class TrainOutput(NamedTuple):
    clusters: np.ndarray  # [N] int32 global cluster ids; 0 == noise
    flags: np.ndarray  # [N] int8 Core/Border/Noise
    partitions: List[Tuple[int, np.ndarray]]  # (id, float main rect [4])
    n_clusters: int
    stats: dict


@dataclasses.dataclass(frozen=True)
class CampaignLeg:
    """One chunk-leased PARTIAL run of the banded device phase
    (dbscan_tpu/campaign.py). The leg computes ONLY the p1 chunks in
    ``chunks`` — every other banded group's dispatch is skipped — saves
    each completed chunk's pulled artifacts at its PLAN-derived chunk
    index, and returns a partial :class:`TrainOutput` (empty labels,
    ``stats["campaign_partial"] = True``) BEFORE the merge phases. The
    chunk indices come from the same accumulation rule ``_on_plan``
    mirrors, so independently-leased legs produce exactly the chunk
    files a single sequential run would, and a final unrestricted run
    over the fully-banked dir loads them all and merges — labels
    byte-identical by the checkpoint adoption contract. Requires the
    banded compact path and a ``checkpoint_dir``.

    ``chunks`` empty = plan-only leg: no dispatch at all; the leg packs,
    writes ``progress.json`` (chunks_total), and reports the plan in its
    partial stats.

    ``tier`` = "cpu" routes every leased dispatch through the
    per-group CPU degradation kernel (the whole-lease generalization of
    the faults.py per-group fallback) — same algebra, labels unchanged.

    ``kill_after`` > 0 is the deterministic worker-kill drill: after
    that many chunks of this leg have been pulled AND saved, the leg
    raises ``faults.FatalDeviceFault`` at the ``campaign`` site — the
    abort guard banks progress + dumps the flight recorder exactly as
    for a real mid-leg death, and the campaign worker accounts the
    steal.

    ``on_chunk(ci)`` fires after each chunk save (lease completion +
    heartbeat); ``on_progress()`` fires after each leased GROUP
    dispatch — the fine-grained heartbeat, so a lease whose first
    chunk takes longer than the expiry window is still provably alive
    (only a leg making NO forward progress for a whole window reads
    as wedged)."""

    chunks: FrozenSet[int]
    tier: str = "device"
    kill_after: int = 0
    kill_ordinal: int = -1
    on_chunk: Optional[Callable[[int], None]] = None
    on_progress: Optional[Callable[[], None]] = None


def clear_compile_cache() -> None:
    """Drop all cached jitted executors (and the Mesh objects and XLA
    executables they retain). For long-lived processes sweeping many
    configurations or meshes."""
    _compiled_block_cached.cache_clear()
    _compiled_block_resident_cached.cache_clear()
    _compiled_banded_p1.cache_clear()
    from dbscan_tpu.ops.sparse import _compiled_leaf_batch_cached

    _compiled_leaf_batch_cached.cache_clear()


def _compiled_block(
    eps: float,
    min_points: int,
    engine: str,
    metric: str,
    use_pallas: bool,
    batch: Optional[int],
    mesh,
):
    # propagation mode resolved BEFORE the cache key (ops/propagation.py
    # contract for cached builders): an in-process knob flip re-traces
    from dbscan_tpu.ops.propagation import prop_mode

    return _compiled_block_cached(
        eps, min_points, engine, metric, use_pallas, batch, mesh,
        prop_mode(),
    )


@functools.lru_cache(maxsize=256)
def _compiled_block_cached(
    eps: float,
    min_points: int,
    engine: str,
    metric: str,
    use_pallas: bool,
    batch: Optional[int],
    mesh,
    mode: str,
):
    """Build (once per distinct config+mesh) the jitted per-group executor.

    The jit wrapper MUST be cached at module level: jax.jit keys its
    trace/compile cache on the wrapped function's identity, so a fresh
    closure per train() call would re-trace and re-XLA-compile every bucket
    group on every call (and every streaming micro-batch update), defeating
    the geometric width ladder's whole purpose.
    """

    def one(args):
        pts, msk = args
        r = local_dbscan(
            pts,
            msk,
            eps,
            min_points,
            engine=engine,
            metric=metric,
            use_pallas=use_pallas,
            mode=mode,
        )
        return r.seed_labels, r.flags

    def block(pts_blk, msk_blk):
        if batch is None:
            # Pallas path: plain lax.map (scan of the unbatched body) — with
            # batch_size set, lax.map lowers through vmap even at size 1,
            # which would vmap the pallas_calls over the on-device
            # while_loop; the sweeps already fill the chip, so keep it
            # strictly sequential.
            seeds, flags = lax.map(one, (pts_blk, msk_blk))
        else:
            seeds, flags = lax.map(one, (pts_blk, msk_blk), batch_size=batch)
        # Global core count via all-reduce over the mesh. Derivable on host,
        # but kept in the compiled step deliberately: it keeps one real ICI
        # collective in the production program (the comms-backend analog of
        # the reference's aggregate-to-driver pass) so multichip dryruns
        # validate the communication path, at the cost of one fused scalar.
        ncore = jnp.sum(flags == CORE, dtype=jnp.int32)
        if mesh is not None:
            ncore = lax.psum(ncore, mesh_mod.parts_axes(mesh))
        return seeds, flags, ncore

    if mesh is None:
        return jax.jit(block)
    spec = mesh_mod.parts_spec(mesh)
    return jax.jit(
        mesh_mod.shard_map(
            block,
            mesh=mesh,
            in_specs=(spec, spec),
            out_specs=(spec, spec, PartitionSpec()),
        )
    )


@functools.lru_cache(maxsize=256)
def _compiled_banded_p1(
    eps: float,
    min_points: int,
    slab: int,
    batch: Optional[int],
    mesh,
    use_pallas: bool = False,
    pallas_sp: bool = False,
):
    """Jitted per-group phase-1 executor for the banded engine (counts +
    core + cell-edge bitmask sweeps, dbscan_tpu/ops/banded.py — or their
    Pallas ports: ops/pallas_banded.py, or the scalar-prefetch variant
    ops/pallas_banded_sp.py under DBSCAN_PALLAS_SP=1); cached like
    :func:`_compiled_block`."""
    if use_pallas and pallas_sp:
        from dbscan_tpu.ops.pallas_banded_sp import (
            banded_phase1_pallas_sp as phase1,
        )
    elif use_pallas:
        from dbscan_tpu.ops.pallas_banded import (
            banded_phase1_pallas as phase1,
        )
    else:
        from dbscan_tpu.ops.banded import banded_phase1 as phase1

    def one(args):
        pts, msk, rel, sp, sl, cx = args
        return phase1(
            pts, msk, rel, sp, sl, cx, eps, min_points, slab=slab
        )

    def block(pts, msk, rel, sp, sl, cx):
        counts, core, bits = lax.map(
            one, (pts, msk, rel, sp, sl, cx), batch_size=batch
        )
        # Global core count via all-reduce over the mesh: keeps one real
        # ICI collective in the banded production program (the dense path
        # has its own, _compiled_block) so multichip dryruns validate the
        # communication path even for all-banded workloads.
        ncore = jnp.sum(core, dtype=jnp.int32)
        if mesh is not None:
            ncore = lax.psum(ncore, mesh_mod.parts_axes(mesh))
        # counts are consumed on-device (core = counts >= minPts) and
        # nothing downstream reads them — returning them would pin
        # 4 B/slot of HBM across every banded group until the postpass
        return core, bits, ncore

    if mesh is None:
        return jax.jit(block)
    spec = mesh_mod.parts_spec(mesh)
    return jax.jit(
        mesh_mod.shard_map(
            block,
            mesh=mesh,
            in_specs=(spec,) * 6,
            out_specs=(spec, spec, PartitionSpec()),
            # pallas_call's out_shape carries no varying-mesh-axes
            # annotation, so the vma checker rejects it under shard_map;
            # the XLA path keeps the check
            check_vma=not use_pallas,
        )
    )


def _banded_batch(group, mesh) -> int:
    """Partitions per vmapped lax.map step for a banded group: bound the
    [T, R, S]-tile transients to a fixed HBM element budget (scaled by
    the coordinate plane count — 3 for spherical-chord payloads)."""
    from dbscan_tpu.parallel.binning import BANDED_ROWS

    p_total, b = group.points.shape[:2]
    planes = max(1, group.points.shape[2] - 1)
    per_part = b * (BANDED_ROWS * group.banded.slab) * planes
    mem_cap = max(1, int(1.2e9) // per_part)
    return max(1, min(8, mem_cap, p_total // max(1, mesh_size(mesh))))


def _compiled_block_resident(
    eps: float,
    min_points: int,
    engine: str,
    metric: str,
    batch: Optional[int],
    mesh,
):
    # propagation mode resolved BEFORE the cache key, as _compiled_block
    from dbscan_tpu.ops.propagation import prop_mode

    return _compiled_block_resident_cached(
        eps, min_points, engine, metric, batch, mesh, prop_mode()
    )


@functools.lru_cache(maxsize=256)
def _compiled_block_resident_cached(
    eps: float,
    min_points: int,
    engine: str,
    metric: str,
    batch: Optional[int],
    mesh,
    mode: str,
):
    """Resident-payload variant of :func:`_compiled_block`: the full
    [N, D] row array (bf16, uploaded ONCE by the spill phase) stays on
    device and each partition's rows are GATHERED inside the program —
    the group ships an int32 index table instead of a [P, B, D] payload,
    ~(2*D)x less upload on the ~60 MB/s tunnel for 512-d cosine data.
    Quantization: kernels measure on bf16-rounded values in f32; the
    driver widens the spill halo by the matching q (train_arrays)."""

    def one_r(x):
        def one(args):
            ii, msk = args
            pts = x[ii].astype(jnp.float32)
            r = local_dbscan(
                pts,
                msk,
                eps,
                min_points,
                engine=engine,
                metric=metric,
                use_pallas=False,
                mode=mode,
            )
            return r.seed_labels, r.flags

        return one

    def block(x, idx, msk_blk):
        seeds, flags = lax.map(
            one_r(x), (idx, msk_blk), batch_size=batch
        )
        ncore = jnp.sum(flags == CORE, dtype=jnp.int32)
        if mesh is not None:
            ncore = lax.psum(ncore, mesh_mod.parts_axes(mesh))
        return seeds, flags, ncore

    if mesh is None:
        return jax.jit(block)
    spec = mesh_mod.parts_spec(mesh)
    return jax.jit(
        mesh_mod.shard_map(
            block,
            mesh=mesh,
            in_specs=(PartitionSpec(), spec, spec),
            out_specs=(spec, spec, PartitionSpec()),
        )
    )


def _cpu_fallback_allowed(cfg: DBSCANConfig) -> bool:
    """Per-group CPU degradation is a process-local decision: in a
    multi-process job one host degrading while the others dispatch
    would desynchronize the collective sequence, so it is forced off
    there (the retry/backoff path still applies everywhere)."""
    return bool(
        getattr(cfg, "fault_cpu_fallback", True)
        and not mesh_mod.multiprocess()
    )


def _cpu_dispatch_group(
    group, cfg: DBSCANConfig, mesh, kernel_eps=None, kernel_metric=None,
    resident_unit=None,
):
    """Per-group CPU degradation for the dense/resident kernel family:
    the SAME ``local_dbscan`` algebra, one partition at a time, pinned
    to the host jax CPU backend. Labels are identical by construction
    (one engine, another backend; the Pallas variant's XLA parity is
    pinned by tests), so a degraded run's output equals the healthy
    run's. Results re-enter the dispatch output layout (sharded like a
    device dispatch would have produced) so downstream pulls stay
    oblivious."""
    eps = float(kernel_eps if kernel_eps is not None else cfg.eps)
    metric = kernel_metric if kernel_metric is not None else cfg.metric
    msk = np.asarray(group.mask)
    if group.points is None:
        import ml_dtypes

        # resident gather path: reproduce the device's bf16-stored rows
        # rounded into f32 (the quantization the spill halo was widened
        # for) so the degraded group measures what the device would have
        idx = np.where(group.point_idx >= 0, group.point_idx, 0)
        pts = (
            np.asarray(resident_unit)[idx]
            .astype(ml_dtypes.bfloat16)
            .astype(np.float32)
        )
    else:
        pts = np.asarray(group.points)
    cpu = jax.devices("cpu")[0]
    seeds = np.empty(msk.shape, np.int32)
    flags = np.empty(msk.shape, np.int8)
    with jax.default_device(cpu):
        for p in range(msk.shape[0]):
            r = local_dbscan(
                jnp.asarray(pts[p]),
                jnp.asarray(msk[p]),
                eps,
                int(cfg.min_points),
                engine=cfg.engine.value,
                metric=metric,
                use_pallas=False,
            )
            seeds[p] = np.asarray(r.seed_labels)
            flags[p] = np.asarray(r.flags)
    ncore = np.int32((flags == CORE).sum())
    return (
        mesh_mod.shard_host_array(mesh, seeds),
        mesh_mod.shard_host_array(mesh, flags),
        ncore,
    )


@functools.lru_cache(maxsize=32)
def _cpu_banded_p1_fn(eps: float, min_points: int, slab: int):
    """Jitted single-partition banded phase-1 for the CPU degradation
    path (compiles once per config on the host backend)."""
    from dbscan_tpu.ops.banded import banded_phase1

    def one(pts, msk, rel, sp, sl, cx):
        return banded_phase1(
            pts, msk, rel, sp, sl, cx, eps, min_points, slab=slab
        )

    return jax.jit(one)


def _cpu_dispatch_banded_p1(group, cfg: DBSCANConfig, mesh, kernel_eps=None):
    """Per-group CPU degradation for the banded family: the XLA
    ``banded_phase1`` sweeps partition-by-partition on the host backend
    (the Pallas ports are device-only; their XLA parity is pinned by
    tests). Output re-enters the (core, bits, ncore) dispatch layout."""
    ext = group.banded
    eps = float(kernel_eps if kernel_eps is not None else cfg.eps)
    fn = _cpu_banded_p1_fn(eps, int(cfg.min_points), int(ext.slab))
    cpu = jax.devices("cpu")[0]
    cores, bitses = [], []
    with jax.default_device(cpu):
        for p in range(group.mask.shape[0]):
            _counts, core_p, bits_p = fn(
                jnp.asarray(group.points[p]),
                jnp.asarray(group.mask[p]),
                jnp.asarray(ext.rel_starts[p]),
                jnp.asarray(ext.spans[p]),
                jnp.asarray(ext.slab_starts[p]),
                jnp.asarray(ext.cx[p]),
            )
            cores.append(np.asarray(core_p))
            bitses.append(np.asarray(bits_p))
    core = np.stack(cores)
    bits = np.stack(bitses)
    return (
        mesh_mod.shard_host_array(mesh, core),
        mesh_mod.shard_host_array(mesh, bits),
        np.int32(core.sum()),
    )


def _dispatch_partitions(
    group, cfg: DBSCANConfig, mesh, kernel_eps=None, kernel_metric=None,
    resident_x=None, resident_unit=None,
):
    """Fan the dense/pallas local kernel out over the partition axis (async
    dispatch), under fault supervision (dbscan_tpu/faults.py): transient
    device faults retry with backoff, RESOURCE_EXHAUSTED halves the
    lax.map batch budget before retrying, and a persistent fault degrades
    THIS group to the CPU ``local_dbscan`` engine instead of aborting.

    Inside each mesh shard, partitions are processed with lax.map (bounded
    memory: one adjacency at a time, `batch` of them in flight) — the moral
    equivalent of one Spark executor looping its assigned tasks
    (DBSCAN.scala:150-154), but compiled. Returns device arrays without
    blocking so successive bucket groups overlap on the device queue
    (supervision blocks per group only when a fault spec is active —
    faults.sync_mode).

    kernel_eps/kernel_metric override cfg's user-facing values when the
    kernel measures in a different space than the user's metric (spherical
    chord coordinates with a chord threshold, ops/sphere.py).
    """
    p_total, b = group.mask.shape[:2]
    # vmap small batches of partitions for utilization, capped so the
    # batched per-partition [B, B] intermediates stay within a fixed HBM
    # element budget — wide buckets run narrower batches. Pallas path:
    # strictly sequential (batch=None -> unbatched lax.map).
    if cfg.use_pallas:
        batch = None
    else:
        # backstop for force-dense expert runs (the single-partition
        # metrics fail fast in train_arrays before any packing)
        _check_dense_width(
            b,
            int(group.row_counts.max())
            if group.row_counts is not None
            else b,
        )
        mem_cap = max(1, int(1.2e9) // (b * b))
        batch = max(1, min(8, mem_cap, p_total // max(1, mesh_size(mesh))))
    eps = float(kernel_eps if kernel_eps is not None else cfg.eps)
    metric = kernel_metric if kernel_metric is not None else cfg.metric
    if group.points is None:
        # resident-payload gather dispatch (cosine spill route): the
        # payload upload already happened once, for the spill phase
        idx32 = np.where(
            group.point_idx >= 0, group.point_idx, 0
        ).astype(np.int32)

        def attempt(budget):
            fn = _compiled_block_resident(
                eps, int(cfg.min_points), cfg.engine.value, metric,
                budget, mesh,
            )
            return obs_compile.tracked_call(
                "dispatch.resident",
                fn,
                resident_x,
                mesh_mod.shard_host_array(mesh, idx32),
                mesh_mod.shard_host_array(mesh, group.mask),
            )

    else:

        def attempt(budget):
            fn = _compiled_block(
                eps, int(cfg.min_points), cfg.engine.value, metric,
                bool(cfg.use_pallas), budget, mesh,
            )
            return obs_compile.tracked_call(
                "dispatch.dense",
                fn,
                mesh_mod.shard_host_array(mesh, group.points),
                mesh_mod.shard_host_array(mesh, group.mask),
            )

    fallback = None
    if _cpu_fallback_allowed(cfg):
        fallback = lambda: _cpu_dispatch_group(  # noqa: E731
            group, cfg, mesh, kernel_eps, kernel_metric, resident_unit
        )
    # dispatched input bytes: the gather variant ships an index table
    # instead of rows — exactly the transfer the resident design saves,
    # now visible in the counters
    if group.points is None:
        h2d = int(idx32.nbytes) + int(np.asarray(group.mask).nbytes)
    else:
        h2d = int(np.asarray(group.points).nbytes) + int(
            np.asarray(group.mask).nbytes
        )
    obs.count("transfer.h2d_bytes", h2d)
    with obs.span(
        "dispatch.resident" if group.points is None else "dispatch.dense",
        partitions=int(p_total),
        bucket=int(b),
        input_bytes=h2d,
    ) as sp:
        out = faults.supervised(
            faults.SITE_DISPATCH,
            attempt,
            policy=faults.RetryPolicy.from_config(cfg),
            budget=batch,
            fallback=fallback,
            label=f"[{p_total}, {b}]",
        )
        # async dispatch: without a device-sync boundary the span covers
        # the host-side dispatch wall only (DBSCAN_TIME_DEVICE=1 blocks)
        sp.sync(out[0])
    # HBM watermark at the dispatch boundary (no-op when obs disabled
    # or the backend has no allocator stats — CPU)
    obs_memory.sample(
        "dispatch.resident" if group.points is None else "dispatch.dense"
    )
    return out


def _dispatch_banded_p1(group, cfg: DBSCANConfig, mesh, kernel_eps=None):
    """Async phase-1 dispatch for one banded group: (core, bits, ncore)
    — per-slot counts are consumed on-device and deliberately not
    returned (they would pin 4 B/slot across every group, see
    _compiled_banded_p1). kernel_eps overrides cfg.eps when the payload
    is chord coordinates. Supervised like _dispatch_partitions:
    transient faults retry, RESOURCE_EXHAUSTED halves the batch budget,
    persistent faults degrade the group to the CPU banded sweeps."""
    ext = group.banded
    logger.debug(
        "banded group dispatch: points %s slab %d batch %s",
        group.points.shape,
        int(ext.slab),
        _banded_batch(group, mesh),
    )

    def attempt(budget):
        fn = _compiled_banded_p1(
            float(kernel_eps if kernel_eps is not None else cfg.eps),
            int(cfg.min_points),
            int(ext.slab),
            budget,
            mesh,
            use_pallas=bool(cfg.use_pallas),
            pallas_sp=(
                bool(cfg.use_pallas)
                and config_mod.env("DBSCAN_PALLAS_SP")
            ),
        )
        return obs_compile.tracked_call(
            "dispatch.banded_p1",
            fn,
            *(
                mesh_mod.shard_host_array(mesh, a)
                for a in (
                    group.points, group.mask, ext.rel_starts, ext.spans,
                    ext.slab_starts, ext.cx,
                )
            ),
        )

    fallback = None
    if _cpu_fallback_allowed(cfg):
        fallback = lambda: _cpu_dispatch_banded_p1(  # noqa: E731
            group, cfg, mesh, kernel_eps
        )
    h2d = int(
        sum(
            np.asarray(a).nbytes
            for a in (
                group.points, group.mask, ext.rel_starts, ext.spans,
                ext.slab_starts, ext.cx,
            )
        )
    )
    obs.count("transfer.h2d_bytes", h2d)
    with obs.span(
        "dispatch.banded",
        shape=tuple(int(s) for s in group.points.shape),
        slab=int(ext.slab),
        input_bytes=h2d,
    ) as sp:
        out = faults.supervised(
            faults.SITE_BANDED,
            attempt,
            policy=faults.RetryPolicy.from_config(cfg),
            # Pallas path: strictly sequential (no batch_size -> plain
            # scan); lax.map's vmap lowering would vmap the
            # pallas_calls' manual DMAs
            budget=None if cfg.use_pallas else _banded_batch(group, mesh),
            fallback=fallback,
            label=f"{group.points.shape}",
        )
        sp.sync(out[0])
    obs_memory.sample("dispatch.banded")
    return out


# auto_maxpp heuristic (VERDICT r3 item 7): effective bound >= this
# multiple of the densest 2eps-cell pileup, capped at the known-good
# production bucket width. K=4 keeps several hot cells per partition, so
# halo bands stop dominating the partition area (the dup-2.37 regime).
_MAXPP_PILEUP_K = 4
_MAXPP_AUTO_CAP = 262144


def _effective_maxpp(cfg: DBSCANConfig, counts: np.ndarray) -> int:
    """Partition bound actually handed to the partitioner. The partitioner
    cannot cut inside a 2eps cell (EvenSplitPartitioner.scala:85-92 hits
    the same wall silently), so when the densest cell under-fits the
    requested bound the partitions degenerate to near-single-cell
    rectangles and the eps-halo duplication explodes. Raise the effective
    bound to K x that pileup (capped), loudly; labels are partitioning-
    independent so only layout/perf changes."""
    maxpp = cfg.max_points_per_partition
    if len(counts) == 0:
        return maxpp
    cmax = int(counts.max())
    # the degenerate regime starts where a partition cannot even hold TWO
    # of the densest cells — below that, layouts still amortize their halo
    # over several hot cells and neither the warning nor the raise applies
    if maxpp >= 2 * cmax:
        return maxpp
    # under-fit regime detected: ALWAYS say so (the config contract),
    # whatever the raise decision below turns out to be
    floor = min(_MAXPP_AUTO_CAP, _MAXPP_PILEUP_K * cmax)
    if not cfg.auto_maxpp:
        if floor > maxpp:
            logger.warning(
                "max_points_per_partition=%d under-fits the densest "
                "2eps cell (%d points): partitions degenerate toward "
                "single-cell rectangles and eps-halo duplication grows "
                "(measured 2.4x instance blow-up in this regime); pass "
                "auto_maxpp=True or raise max_points_per_partition "
                "toward %d",
                maxpp, cmax, floor,
            )
        else:
            # nothing to raise toward — same message the auto path gives
            logger.warning(
                "densest 2eps cell holds %d points — more than half of "
                "max_points_per_partition=%d — and no larger bound "
                "would help (cap %d): halo duplication may grow with "
                "near-single-cell partitions",
                cmax, maxpp, _MAXPP_AUTO_CAP,
            )
        return maxpp
    if floor <= maxpp:
        logger.warning(
            "densest 2eps cell holds %d points — more than half of "
            "max_points_per_partition=%d — and auto_maxpp cannot raise "
            "the bound further (cap %d): halo duplication may grow with "
            "near-single-cell partitions",
            cmax, maxpp, _MAXPP_AUTO_CAP,
        )
        return maxpp
    logger.warning(
        "max_points_per_partition=%d under-fits the densest 2eps cell "
        "(%d points): raising the effective bound to %d to keep halo "
        "duplication bounded (auto_maxpp=False keeps the requested bound)",
        maxpp, cmax, floor,
    )
    return floor


def _group_flops(g) -> int:
    """Arithmetic work of one banded group's two phase-1 sweeps, from its
    exact dispatched (padded) shapes: per (point slot, window row, slab
    element) each sweep computes D differences, D squares, D-1 adds and 1
    compare (~3D flops; window/mask logic excluded — a conservative
    count). Feeds the MFU accounting (VERDICT r3 item 3)."""
    p_g, b_g = g.points.shape[:2]
    return (
        2 * p_g * b_g * binning.BANDED_ROWS
        * int(g.banded.slab) * 3 * g.points.shape[2]
    )


def _group_bytes(g) -> int:
    """HBM traffic of one banded group's two phase-1 sweeps, from its
    dispatched shapes: each (partition, block) fetches its BANDED_ROWS
    union slabs once per sweep ([5, S, D] dynamic-slice reads, shared by
    the block's BANDED_BLOCK rows) and writes per-slot outputs (counts
    i32 + core bits + cell-edge bitmask i32). Feeds the roofline
    accounting (VERDICT r4 item 6): sweep arithmetic is VPU elementwise
    work, so the binding resource is HBM bandwidth or VPU f32 issue —
    never the MXU the old MFU ratio divided by."""
    p_g, b_g = g.points.shape[:2]
    d = g.points.shape[2]
    dt = g.points.dtype.itemsize
    nb = b_g // binning.BANDED_BLOCK
    # per slab element across both sweeps: counts reads d planes (dt) +
    # mask (1 B); bits re-reads those plus cx (4 B) + core (1 B)
    reads = (
        p_g * nb * binning.BANDED_ROWS * int(g.banded.slab)
        * (2 * d * dt + 7)
    )
    writes = p_g * b_g * (4 + 1 + 4)
    return reads + writes


def _resolved_prop_mode(cellcc_dev: dict) -> str:
    """The propagation mode the run's stats report: the per-run latch
    when the device finalize resolved one, else the live knob (host-
    oracle and dense runs still say which mode their window_cc-family
    fixed points would ride)."""
    if cellcc_dev.get("prop_mode"):
        return str(cellcc_dev["prop_mode"])
    from dbscan_tpu.ops import propagation as prop_mod

    return prop_mod.prop_mode()


def _pad_idx(pos: np.ndarray, shape_floors=None) -> np.ndarray:
    """Pad a flat gather-index vector up the bucket ladder so the device
    gather compiles once per rung, not per data-dependent count (padding
    gathers position 0; callers slice the pull back to the true length).
    With shape_floors (streaming), the rung ratchets monotonically so
    steady-state batches reuse ONE gather signature."""
    k = binning._ratchet(
        shape_floors,
        "gather",
        binning._ladder_width(max(1, len(pos)), 4096),
    )
    out = np.zeros(k, dtype=np.int32)
    out[: len(pos)] = pos
    return out


def _local_ids_flat(
    inst_part: np.ndarray, inst_seed: np.ndarray, n_parts: int, max_b: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Dense 1-based per-partition cluster ids from flat per-instance seed
    labels.

    Returns (loc [M] int32 local ids with 0 for noise, uniq_part [K],
    uniq_loc [K], labeled [M] bool, inv [L] ranks into the unique table for
    the labeled instances) where (uniq_part, uniq_loc) enumerate all
    distinct non-noise (partition, local id) pairs sorted by partition then
    id — the deterministic ordering we feed the global-id assignment
    (reference localClusterIds, DBSCAN.scala:194-200). Seed row-index order
    IS the reference's fold order, so dense-ranking seeds per partition
    reproduces its sequential numbering. `inv` lets the caller map labeled
    instances straight to per-unique-cluster tables (global ids) without
    re-searching.
    """
    labeled = inst_seed != SEED_NONE
    loc = np.zeros(len(inst_part), dtype=np.int32)
    key = inst_part[labeled] * np.int64(max_b + 1) + inst_seed[labeled]
    if key.size == 0:
        return (
            loc, np.empty(0, np.int64), np.empty(0, np.int32), labeled,
            np.empty(0, np.int64),
        )
    u, inv, _ = geo.group_by_int_key(key, max_key=n_parts * (max_b + 1))
    upart = u // (max_b + 1)
    first = np.searchsorted(upart, np.arange(n_parts))
    uloc = (np.arange(len(u)) - first[upart] + 1).astype(np.int32)
    loc[labeled] = uloc[inv]
    return loc, upart, uloc, labeled, inv


def _classify_instances(
    pts: np.ndarray,
    cells: np.ndarray,
    cell_inv: np.ndarray,
    rects_int: np.ndarray,
    margins: binning.Margins,
    inst_part: np.ndarray,
    inst_ptidx: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray]:
    """Merge-band membership per point + inner membership per instance,
    resolved per 2eps-CELL wherever the cell decides it outright.

    With inner = main shrunk by eps and cells of side 2eps, a cell whose
    indices sit >= 1 inside the partition's integer rect on every side is
    STRICTLY interior to inner for all its points, with half a cell of
    float slack (2eps*(x+1) - (main.x + eps) = eps >> ulp): its instances
    are inner and never band, no float test needed. Only the boundary-ring
    cells (a perimeter minority) take the exact per-point containment
    tests (DBSCAN.scala:161-167, :304-315). Returns (band_any [N] bool,
    inst_inner [M] bool aligned with inst_part/inst_ptidx).
    """
    native = _native.classify_instances(
        pts, cells, cell_inv, rects_int, margins.inner, margins.main,
        inst_part, inst_ptidx,
    )
    if native is not None:
        return native
    icell = cell_inv[inst_ptidx]
    ccx = cells[icell, 0]
    ccy = cells[icell, 1]
    r = rects_int[inst_part]  # [M, 4] int
    interior = (
        (ccx >= r[:, 0] + 1)
        & (ccx <= r[:, 2] - 2)
        & (ccy >= r[:, 1] + 1)
        & (ccy <= r[:, 3] - 2)
    )
    inst_inner = interior.copy()
    band_any = np.zeros(len(pts), dtype=bool)
    ring = np.flatnonzero(~interior)
    if ring.size:
        rp = inst_part[ring]
        ri = inst_ptidx[ring]
        p2 = pts[ri, :2]  # index both axes at once: no [M, D] intermediate
        inn = geo.almost_contains(margins.inner[rp], p2)
        inst_inner[ring] = inn
        inband = geo.contains_point(margins.main[rp], p2) & ~inn
        band_any[ri[inband]] = True
    return band_any, inst_inner


def _band_membership(
    points: np.ndarray,
    margins: binning.Margins,
    part_ids: np.ndarray,
    point_idx: np.ndarray,
) -> np.ndarray:
    """any-partition merge-band membership per original point:
    main.contains && !inner.almost_contains for some partition
    (DBSCAN.scala:161-167).

    Evaluated over the halo-duplication instance list rather than the full
    [P, N] cross product: main is a subset of outer, so every (partition,
    point) pair with main.contains already appears among the duplicated
    instances — O(instances) single-rect checks instead of O(P*N).
    """
    pts = np.asarray(points, dtype=np.float64)[:, :2]
    out = np.zeros(len(pts), dtype=bool)
    p2 = pts[point_idx]
    band = geo.contains_point(
        margins.main[part_ids], p2
    ) & ~geo.almost_contains(margins.inner[part_ids], p2)
    out[point_idx[band]] = True
    return out



def finalize_merge(
    inst_part: np.ndarray,
    inst_ptidx: np.ndarray,
    inst_seed: np.ndarray,
    inst_flag: np.ndarray,
    cand: np.ndarray,
    inst_inner: np.ndarray,
    n: int,
    p_true: int,
    max_b: int,
    canonical: bool = False,
    mesh=None,
    shape_floors: Optional[dict] = None,
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Steps 6-9 of the reference pipeline (DBSCAN.scala:179-283) on flat
    instance tables: deterministic per-partition cluster enumeration,
    union-find over clusters sharing a merge-candidate point, global-id
    assignment, and the inner/band relabel + dedup scatter into per-point
    outputs. Returns (clusters [n] int32, flags [n] int8, n_clusters).

    Inputs: per-instance (partition, point row, seed label, flag) plus the
    merge classification — ``cand`` (instance participates in the merge
    dedup) and ``inst_inner`` (instance authoritative for its point).
    Shared by the grid/spill drivers (train_arrays) and the sparse cosine
    front-end (ops/sparse.py), whose decompositions produce the same
    instance-table shape.

    ``mesh``: with a multi-device mesh (and ``DBSCAN_MESH_MERGE`` on),
    the union step — the one phase here that grows with the mesh — runs
    as the collective halo-merge (parallel/halo.py): the border-union
    edges shard over the mesh axes and iterate to the union-find fixed
    point with ppermute/psum-style neighbor collectives, byte-identical
    numbering included. None (or a 1-device mesh) keeps the host
    union-find. ``shape_floors`` is the streaming ratchet dict for the
    halo kernel's padded widths.

    ``canonical``: renumber the final global ids so clusters appear in
    order of their minimum member point row. The default numbering
    follows the unique (partition, local-id) RANK order, which depends
    on the partition layout — fine for the 2-D grid (deterministic in
    the data), but the spill tree's layout depends on pivot choice, and
    the level-synchronous device build (spill_device.build_level_tree)
    must produce labels IDENTICAL to the host recursion's even though
    the two pick different pivots. Cluster MEMBERSHIP is decomposition-
    independent (the coverage contract + this merge, PARITY.md "Spill
    tree"), so numbering by min member row makes the full label vector
    decomposition-independent too. Spill callers pass True."""
    # 6. local ids + deterministic cluster enumeration.
    inst_loc, upart, uloc, labeled_inst, inst_urank = _local_ids_flat(
        inst_part, inst_seed, p_true, max_b
    )

    # 7. merge: union clusters observed on the same halo point.
    # Edges are keyed by dense RANK into the unique (part, loc) table —
    # rank(part, loc) = first[part] + loc - 1 (the inverse of
    # _local_ids_flat's numbering) — so the packed dedup key spans at
    # most K^2 < 2^62 for ANY id space (no narrow/wide split), and the
    # native union-find indexes its node arrays directly, no lookup.
    n_uniq = len(upart)
    first_of_part = np.searchsorted(upart, np.arange(p_true))
    ua = ub = np.empty(0, np.int64)
    nz = cand & (inst_flag != NOISE)
    if nz.any():
        k = inst_ptidx[nz]
        kp = inst_part[nz]
        kl = inst_loc[nz]
        order = _native.argsort_ints(k)
        k, kp, kl = k[order], kp[order], kl[order]
        starts = np.flatnonzero(np.r_[True, k[1:] != k[:-1]])
        group_of = np.repeat(np.arange(len(starts)), np.diff(np.r_[starts, len(k)]))
        first = starts[group_of]
        rest = np.arange(len(k)) != first
        # dedup to unique cluster-pair edges before the union phase: the
        # instance count can be huge, the edge count is small. One packed
        # int64 key instead of np.unique(axis=0) — the latter sorts a void
        # view, measured ~10x slower at 10M instances.
        ranks = first_of_part[kp] + kl - 1
        span = np.int64(max(1, n_uniq))
        uniq_e = np.unique(ranks[first[rest]] * span + ranks[rest])
        ua, ub = np.divmod(uniq_e, span)

    # union-find + global-id assignment over the rank edges; gid_of_u
    # aligns with upart/uloc by rank (reference DBSCAN.scala:206-222).
    # On a multi-device mesh the union runs IN the mesh — the collective
    # halo-merge fixed point (parallel/halo.py) — instead of on the
    # driver; numbering is byte-identical by the first-appearance ==
    # min-rank argument in that module's docstring.
    from dbscan_tpu.parallel import halo

    if halo.merge_active(mesh):
        n_clusters, gid_of_u = halo.collective_merge(
            ua, ub, n_uniq, mesh, shape_floors=shape_floors
        )
    else:
        n_clusters, gid_of_u = uf_components(ua, ub, n_uniq)
    logger.info("Total Clusters: %d, Unique: %d", n_uniq, n_clusters)

    # per-instance global id (0 for noise): labeled instances carry their
    # rank into the unique table already (no re-search)
    gid_nat = (
        _native.build_inst_gid(labeled_inst, inst_urank, gid_of_u)
        if inst_urank.size
        else None
    )
    if gid_nat is not None:
        inst_gid = gid_nat
    else:
        inst_gid = np.zeros(len(inst_part), dtype=np.int32)
        if inst_urank.size:
            inst_gid[labeled_inst] = gid_of_u[inst_urank]

    # 8. relabel + dedup into per-point outputs.
    res_cluster = np.zeros(n, dtype=np.int32)
    res_flag = np.full(n, NOISE, dtype=np.int8)
    assigned = np.zeros(n, dtype=bool)

    # inner instances: at most one per point (mains have disjoint interiors)
    ii = np.flatnonzero(inst_inner)
    if not _native.scatter_sel(
        ii, inst_ptidx, inst_gid, inst_flag, res_cluster, res_flag, assigned
    ):
        res_cluster[inst_ptidx[ii]] = inst_gid[ii]
        res_flag[inst_ptidx[ii]] = inst_flag[ii]
        assigned[inst_ptidx[ii]] = True

    # merge-band instances: dedup by point, prefer Core > Border > Noise,
    # then lower partition id (deterministic; reference keeps last non-noise,
    # DBSCAN.scala:257-267 — same global id either way)
    ci = np.flatnonzero(cand & ~inst_inner)
    if ci.size:
        # packed single key replaces np.lexsort: primary point, then flag,
        # then partition (flag < 4, partition < p_true; no overflow for
        # any N * p_true < 2^61). The native call fuses the key build,
        # the stable argsort, and the first-per-point sweep.
        ck = _native.band_dedup(ci, inst_ptidx, inst_flag, inst_part, p_true)
        if ck is None:
            order = _native.argsort_ints(
                (inst_ptidx[ci] * 4 + inst_flag[ci]) * np.int64(p_true)
                + inst_part[ci]
            )
            ci = ci[order]
            keep = np.r_[True, inst_ptidx[ci][1:] != inst_ptidx[ci][:-1]]
            ck = ci[keep]
        if not _native.scatter_sel(
            ck, inst_ptidx, inst_gid, inst_flag, res_cluster, res_flag,
            assigned,
        ):
            res_cluster[inst_ptidx[ck]] = inst_gid[ck]
            res_flag[inst_ptidx[ck]] = inst_flag[ck]
            assigned[inst_ptidx[ck]] = True

    if not assigned.all():
        # fp-edge fallback: label from any instance (first occurrence) —
        # vectorized: one stray point at 100M scale must not trigger an
        # interpreted O(instances) loop
        missing = np.flatnonzero(~assigned)
        logger.warning(
            "%d points fell outside inner+band; using first instance",
            len(missing),
        )
        if inst_ptidx.size:
            uniq_pt, first_j = np.unique(inst_ptidx, return_index=True)
            pos = np.searchsorted(uniq_pt, missing)
            pos_c = np.minimum(pos, len(uniq_pt) - 1)
            hit = uniq_pt[pos_c] == missing
            m_hit = missing[hit]
            j = first_j[pos_c[hit]]
            res_cluster[m_hit] = inst_gid[j]
            res_flag[m_hit] = inst_flag[j]
    if canonical and n_clusters:
        # renumber by minimum member row: one O(n) scatter-min + an
        # O(K log K) argsort over the (small) cluster count. Noise (0)
        # stays 0.
        first = np.full(n_clusters + 1, n, dtype=np.int64)
        np.minimum.at(first, res_cluster, np.arange(n, dtype=np.int64))
        order = np.argsort(first[1:], kind="stable")
        remap = np.empty(n_clusters + 1, dtype=np.int32)
        remap[0] = 0
        remap[1:][order] = np.arange(1, n_clusters + 1, dtype=np.int32)
        res_cluster = remap[res_cluster]
    return res_cluster, res_flag, n_clusters


def _resume_from_premerge(state: dict, t_start: float) -> TrainOutput:
    """Finish a checkpointed run: the saved flat instance tables go straight
    into finalize_merge — decomposition, packing, and the device phase are
    skipped entirely (parallel/checkpoint.py has the recovery story).

    The checkpoint's scalars ARE the fresh run's core stats dict (one
    schema, saved verbatim); only n_clusters, the resume marker, and the
    timings are added here."""
    a, s = state["arrays"], state["scalars"]
    res_cluster, res_flag, n_clusters = finalize_merge(
        a["inst_part"], a["inst_ptidx"], a["inst_seed"], a["inst_flag"],
        a["cand"], a["inst_inner"],
        int(s["n_points"]), int(s["n_partitions"]), int(s["bucket_size"]),
        # spill runs use canonical ids (min-member-row numbering); the
        # saved scalars say which decomposition produced these tables,
        # so a resumed run numbers exactly like the fresh one would
        canonical=bool(s.get("spill_tree", False)),
    )
    rects = a["rects"]
    partitions = [(i, rects[i]) for i in range(len(rects))]
    now = time.perf_counter()
    obs.add_span(
        "train.resume", t_start, now, n=int(s.get("n_points", 0))
    )
    obs.flush()
    stats = {
        **s,
        "n_clusters": n_clusters,
        "resumed_from_checkpoint": True,
        "timings": {
            "merge_s": round(now - t_start, 6),
            "total_s": round(now - t_start, 6),
        },
    }
    return TrainOutput(res_cluster, res_flag, partitions, n_clusters, stats)


# Resident-payload reuse across train() calls (one entry: the latest
# dataset). The metric-spill payload upload is the measured wall floor of
# the cosine route on a remote-attached chip (1.02 GB bf16 at 1M x 512 ~=
# 31 s over the shared tunnel, BASELINE.md), and DBSCAN's primary
# workflow re-clusters the SAME dataset under different eps/min_points —
# so the device copy AND the derived host unit rows (a second f32 copy
# of the dataset, retained while the entry lives) are cached for the
# lifetime of the caller's input array. Keyed by object identity + a
# FULL-COVERAGE content checksum
# (one memory pass in 8 MiB-bounded blocks, ~0.3 s at 2 GB): identity
# catches reuse,
# the checksum catches any value change anywhere in a reused array —
# including in-window reorders (the per-position multipliers below make
# each 64 KiB window's reduction position-sensitive); gc of the
# input evicts via weakref so the cache can never outlive the data it
# mirrors. Opt out with DBSCAN_RESIDENT_CACHE=0.
_RESIDENT_CACHE: dict = {}
# The cache is shared mutable state on the worker slice since the serve
# ingest thread (dbscan_tpu/serve) started driving train_arrays
# concurrently with main-thread trains; the weakref eviction callback
# additionally fires on WHATEVER thread runs the gc. Reentrant on
# purpose: that callback can fire inside the locked store below when
# the clear() drops the last strong reference chain to a prior key.
_RESIDENT_CACHE_LOCK = _tsan.rlock("driver.resident_cache")


def _resident_cache_drop(key: int) -> None:
    """Weakref eviction: the input array was gc'd, drop its entry."""
    with _RESIDENT_CACHE_LOCK:
        _tsan.access("driver.resident_cache")
        _RESIDENT_CACHE.pop(key, None)


# Odd per-position multipliers for the fingerprint's 64 KiB windows:
# multiplying each u64 word by an odd (hence invertible mod 2^64)
# index-derived constant before the xor/sum reductions makes them
# POSITION-SENSITIVE — swapping two words within one window changes the
# digest (w_i*m_i ^ w_j*m_j != w_j*m_i ^ w_i*m_j for w_i != w_j except
# on measure-zero coincidences the sum lane independently breaks), so a
# value-preserving in-window row swap can no longer silently reuse a
# stale resident payload (ADVICE r5 medium).
_FP_CHUNK = 8192  # u64 words = 64 KiB
_FP_MULT = (
    (np.arange(_FP_CHUNK, dtype=np.uint64) << np.uint64(1))
    + np.uint64(1)
) * np.uint64(0x9E3779B97F4A7C15) | np.uint64(1)
_FP_BLOCK = 128  # chunks multiplied at a time: bounds the u64 product
# temporary at _FP_BLOCK * 64 KiB = 8 MiB regardless of input size (a
# full-size product would double host memory for GB-scale embeddings
# on every cache lookup)


def _pts_fingerprint(pts: np.ndarray) -> bytes:
    h = hashlib.sha1()
    h.update(str((pts.shape, pts.dtype.str)).encode())
    buf = np.ascontiguousarray(pts).view(np.uint8).reshape(-1)
    n8 = (buf.size // 8) * 8
    if n8:
        w = buf[:n8].view(np.uint64)
        # per-64KiB-chunk position-weighted xor AND wraparound sum:
        # every chunk whose bytes change (or reorder) flips at least
        # one digest word
        n_chunks = -(-w.size // _FP_CHUNK)
        xors = np.empty(n_chunks, np.uint64)
        sums = np.empty(n_chunks, np.uint64)
        with np.errstate(over="ignore"):
            for start in range(0, n_chunks, _FP_BLOCK):
                stop = min(start + _FP_BLOCK, n_chunks)
                blk = w[start * _FP_CHUNK : stop * _FP_CHUNK]
                pad = (-blk.size) % _FP_CHUNK
                if pad:
                    blk = np.concatenate(
                        [blk, np.zeros(pad, np.uint64)]
                    )
                prod = blk.reshape(-1, _FP_CHUNK) * _FP_MULT[None, :]
                xors[start:stop] = np.bitwise_xor.reduce(prod, axis=1)
                sums[start:stop] = np.add.reduce(prod, axis=1)
        h.update(xors.tobytes())
        h.update(sums.tobytes())
    h.update(buf[n8:].tobytes())
    return h.digest()


def _resident_payload_lookup(pts: np.ndarray):
    """Returns ((unit rows, device ops, has_zero_norm), fp) on a valid
    hit for this exact (unmutated) array, else (None, fp). ``fp`` is
    the just-computed fingerprint for the store path to reuse (None
    when the cache is disabled or has no entry under this id — the
    store path computes it then). ``has_zero_norm`` records whether
    the data carried zero-norm rows when the entry was built: the
    zero-norm noise screen is config-dependent (it only fires when
    eps + q < 1), so the CALLER must re-apply it on a hit rather than
    assume the prior call's config decided it."""
    if not config_mod.env("DBSCAN_RESIDENT_CACHE"):
        return None, None
    with _RESIDENT_CACHE_LOCK:
        _tsan.access("driver.resident_cache", write=False)
        ent = _RESIDENT_CACHE.get(id(pts))
    if ent is None:
        return None, None
    ref, ent_fp, unit, ops, has_zeros = ent
    fp = _pts_fingerprint(pts)
    if ref() is pts and ent_fp == fp:
        return (unit, ops, has_zeros), fp
    return None, fp


def _resident_payload_cached(
    pts: np.ndarray,
    unit: np.ndarray,
    sdev,
    has_zeros: bool = False,
    fp: bytes = None,
):
    """Build + cache the device-resident bf16 rows for ``unit`` (call
    sites guarantee a preceding lookup missed). The host ``unit`` rows
    are cached alongside — re-deriving them costs ~2.5 s of single-core
    normalization at 1M x 512 — which retains a SECOND f32 copy of the
    dataset for the entry's lifetime (the documented price of the
    sweep fast path; `DBSCAN_RESIDENT_CACHE=0` disables the cache
    entirely)."""
    if not config_mod.env("DBSCAN_RESIDENT_CACHE"):
        return sdev.DeviceNodeOps.from_host(unit)
    key = id(pts)
    if fp is None:
        fp = _pts_fingerprint(pts)
    ops = sdev.DeviceNodeOps.from_host(unit)
    try:
        ref = weakref.ref(pts, lambda _r, k=key: _resident_cache_drop(k))
    except TypeError:  # un-weakref-able input: keep the prior entry
        return ops
    with _RESIDENT_CACHE_LOCK:
        _tsan.access("driver.resident_cache")
        _RESIDENT_CACHE.clear()  # one entry: the latest dataset
        _RESIDENT_CACHE[key] = (ref, fp, unit, ops, bool(has_zeros))
    return ops


def train_arrays(
    points: np.ndarray,
    cfg: DBSCANConfig,
    mesh=None,
    checkpoint_dir: Optional[str] = None,
    campaign: Optional[CampaignLeg] = None,
) -> TrainOutput:
    """Run the full distributed pipeline on host arrays.

    points: [N, >=2]; only the first two columns participate in clustering
    (reference DBSCAN.scala:33-34). Returns per-point global cluster ids and
    flags aligned with the input row order.

    checkpoint_dir: when set, the pre-merge state (partition rects + flat
    per-partition seed tables) is written there once the device phase
    completes, and a later call with the same data/config resumes straight
    at the merge (parallel/checkpoint.py).

    campaign: a :class:`CampaignLeg` makes this call a chunk-leased
    partial leg of a campaign (dbscan_tpu/campaign.py): only the leased
    p1 chunks are computed and saved, and the call returns a partial
    output before the merge. Requires ``checkpoint_dir``.
    """
    cfg = cfg.validate()
    if campaign is not None and checkpoint_dir is None:
        raise ValueError(
            "a CampaignLeg requires checkpoint_dir: leased chunks are "
            "banked as p1chunk restart points, which is the whole point"
        )
    # Per-run slot budgets: rebinding the module-constant names here
    # makes every use below (and in the nested closures) see the LIVE
    # env/profile value — the autotuner and cli --profile set knobs
    # in-process, after the import-time latch (module attrs stay the
    # tests' monkeypatch surface, honored when the env hasn't moved).
    _COMPACT_CHUNK_SLOTS = _live_chunk_slots()
    _INFLIGHT_SLOTS = _live_inflight_slots()
    # observability (dbscan_tpu/obs): activate from DBSCAN_TRACE=path if
    # set — one env lookup; every hook below is a no-op when disabled
    obs.ensure_env()
    raw = np.asarray(points)
    if cfg.use_pallas and cfg.metric not in ("euclidean", "haversine"):
        raise ValueError(
            "use_pallas supports the euclidean metric (any backend) and "
            "haversine (banded route only); got "
            f"{cfg.metric!r}"
        )
    if (
        cfg.use_pallas
        and cfg.metric == "haversine"
        and cfg.neighbor_backend != "banded"
    ):
        # the banded Pallas port's difference-form distance is D-generic
        # (handles the 3-plane chord payload), but the streaming dense
        # kernel is 2-D-only — small buckets on the auto/dense routes
        # would crash at trace time deep in the dense kernel; raise the
        # clearer error here, before any host work
        raise ValueError(
            "use_pallas with metric='haversine' requires "
            "neighbor_backend='banded' (the banded Pallas port consumes "
            "the 3-plane chord payload; the dense streaming kernel is "
            "2-D-only)"
        )
    if cfg.use_pallas and cfg.precision.value != "f32":
        raise ValueError(
            "use_pallas computes distances in f32 only (no f64 on TPU "
            "Pallas; bf16 inputs would silently upcast, diverging from "
            f"the XLA bf16 kernel); got precision={cfg.precision.value!r} "
            "— use Precision.F32 or the XLA path"
        )
    # The geometry paths (grid snapping, rectangles, projections) need
    # f64; the cosine spill path never does — its working arrays are the
    # f32 unit rows — so float embedding inputs keep their own dtype
    # instead of materializing a [N, 512] f64 copy (40 GB at 10M rows).
    if cfg.metric == "cosine" and raw.dtype in (np.float32, np.float64):
        pts = raw
    else:
        pts = np.asarray(raw, dtype=np.float64)  # no-op when already f64
    if pts.ndim != 2 or pts.shape[1] < 2:
        raise ValueError(f"points must be [N, >=2], got {pts.shape}")
    n = len(pts)
    if n == 0:
        return TrainOutput(
            np.empty(0, np.int32),
            np.empty(0, np.int8),
            [],
            0,
            {
                "n_points": 0,
                "n_partitions": 0,
                "bucket_size": 0,
                "n_bucket_groups": 0,
                "n_banded_groups": 0,
                "duplication_factor": 0.0,
                "n_clusters": 0,
                "n_core_instances": 0,
                "projected": False,
                "spill_tree": False,
                "spill_levels": 0,
                "timings": {},
            },
        )

    cell = cfg.minimum_rectangle_size
    timings: dict = {}
    t_start = time.perf_counter()
    # failure accounting is process-global (spill/stream sites share it);
    # this run reports the delta it caused
    fault_snap = faults.counters.snapshot()

    ckpt_fp = None
    if checkpoint_dir is not None and mesh_mod.multiprocess():
        # per-chunk skip/hit decisions are process-local state, but the
        # miss branch issues cross-process collectives — hosts with
        # divergent checkpoint contents would deadlock in them; and
        # every process writing the same files races. The historical
        # hard raise here turned a sharded job into a dead run over a
        # knob that only affects restartability; degrade gracefully
        # instead (BEFORE any partition work starts): the run proceeds
        # un-checkpointed with identical labels, and checkpointed
        # multi-host jobs belong to the campaign driver, whose chunk
        # leases are coordinator-mediated by construction.
        logger.warning(
            "checkpoint_dir=%r ignored in multi-process runs (divergent "
            "per-host checkpoint state would desynchronize the "
            "collective sequence); proceeding WITHOUT checkpointing — "
            "for checkpointed multi-host jobs use the campaign driver "
            "(python -m dbscan_tpu.campaign / campaign.run_frontier), "
            "whose leased p1 chunks are the coordinator-mediated "
            "restart currency",
            checkpoint_dir,
        )
        checkpoint_dir = None
    if checkpoint_dir is not None:
        from dbscan_tpu.parallel import checkpoint as _ckpt

        ckpt_fp = _ckpt.run_fingerprint(pts, cfg)
        state = _ckpt.load_premerge(checkpoint_dir, ckpt_fp)
        if state is not None:
            logger.info("resuming from pre-merge checkpoint in %s",
                        checkpoint_dir)
            return _resume_from_premerge(state, t_start)

    def _mark(phase: str, t0: float) -> float:
        now = time.perf_counter()
        timings[phase] = round(now - t0, 6)
        # retroactive span over the EXACT window the stats dict reports
        # (obs/trace.py design note: the trace and timings never disagree
        # about a phase's wall; postdispatch_s is later re-attributed by
        # subtracting tail pulls — the span keeps the raw window, the
        # pulls appear as their own compact.pull_chunk spans)
        obs.add_span(
            "driver." + (phase[:-2] if phase.endswith("_s") else phase),
            t0,
            now,
            timings_key=phase,
        )
        return now

    # The 2eps-grid spatial decomposition is geometry on the first two
    # coordinates (reference DBSCAN.scala:33-34, :345-356) — natively
    # euclidean. The haversine metric joins it through the equirectangular
    # projection + chord-coordinate embedding (ops/sphere.py): the grid,
    # partitioner, halo, and merge run on projected km while the kernels
    # measure exact great-circle-equivalent chord distances. Datasets the
    # projection cannot serve (antimeridian wrap, near-pole, bf16) keep the
    # single-partition path. Cosine decomposes through metric spill
    # partitioning (below); other user metrics run single-partition.
    spatial = cfg.metric == "euclidean"
    # Euclidean clusters on the first two columns only, like the reference;
    # other metrics see every column (haversine reads lon/lat from the
    # first two, ops/distance.py::_haversine).
    kernel_cols = pts[:, :2] if spatial else pts
    kernel_eps = float(cfg.eps)
    kernel_metric = cfg.metric
    eps_spatial = float(cfg.eps)
    grid_eps = float(cfg.eps)
    sph = None
    if cfg.metric == "haversine" and cfg.precision.value in ("f32", "f64"):
        from dbscan_tpu.ops import sphere

        sph = sphere.embed(
            pts, float(cfg.eps), f32=cfg.precision.value == "f32"
        )
        banded_refused = sph is None or not sph.banded_ok
        refusal_reason = (
            "projection refused: antimeridian/pole/slack"
            if sph is None
            else (
                f"latitude span too wide: cos_ratio {sph.cos_ratio:.3f} "
                "fails the reach margin"
                if banded_refused
                else ""
            )
        )
        if cfg.use_pallas and banded_refused:
            # the upfront guard pinned haversine+pallas to the banded
            # route; with the projection refusing it there is no Pallas
            # kernel that can run this dataset (the dense fallback would
            # crash at trace time) — fail clearly before any host work
            raise ValueError(
                "use_pallas with metric='haversine' needs the spherical "
                f"banded route, but this dataset cannot use it "
                f"({refusal_reason}); drop use_pallas for this data"
            )
        if cfg.neighbor_backend == "banded" and banded_refused:
            # honoring the force would break the banded engine's
            # clique/reach guarantees — degrade loudly, not silently
            logger.warning(
                "neighbor_backend='banded' requested but this spherical "
                "dataset cannot use it (%s); running the %s instead",
                refusal_reason,
                "single-partition dense kernel"
                if sph is None
                else "spatially-decomposed dense kernel",
            )
        if sph is not None:
            spatial = True
            kernel_cols = sph.chord
            kernel_eps = sph.eps_chord
            kernel_metric = "euclidean"
            eps_spatial = sph.eps_spatial
            grid_eps = sph.grid_eps
    # grid-space coordinates for histogram/partition/halo/merge geometry
    grid_pts = sph.proj if sph is not None else pts

    # Cosine: no 2-D grid exists, but the normalized vectors live on the
    # unit hypersphere where cos_dist <= eps iff chord <= sqrt(2*eps) —
    # a metric space where pivot distances obey the triangle inequality,
    # so METRIC SPILL PARTITIONING (parallel/spill.py) supplies the
    # decomposition with the same every-accepted-pair-shares-a-partition
    # contract as the 2eps grid. Merge classification then comes from
    # instance multiplicity, not rectangles.
    rp = None
    spill_info: dict = {}  # spill_partition diagnostics + leaf layout
    resident_ops = None
    resident_unit = None  # host unit rows backing the resident payload
    if cfg.metric == "cosine":
        from dbscan_tpu.parallel import spill

        t0 = time.perf_counter()
        # accepted pairs have measured cos_dist <= eps + q, where q is
        # the kernel's measure quantization — the f32 matmul error grows
        # with the contraction length D, so q scales with it (D * 2^-22
        # is ~4x the worst-case rounding; bf16 keeps its own budget);
        # halo in chord units plus the f32 pivot-distance rounding
        # resident-payload mode: the unit rows live on device in bf16
        # (one upload serves the spill tree AND the leaf gather
        # dispatch), so the kernel measures bf16-rounded values in f32 —
        # q widens to the bf16 value-rounding budget (2*2^-9 dot error,
        # dim-independent for unit rows)
        resident_mode = (
            not mesh_mod.multiprocess()
            and not cfg.use_pallas
            and cfg.precision.value != "f64"
            and spill._spill_device_enabled()
        )
        q_f32 = max(1e-5, pts.shape[1] * 2.0**-22)
        if cfg.precision.value == "bf16":
            q = 0.02
        elif resident_mode:
            # both errors stack in resident mode: bf16 value rounding of
            # the stored rows PLUS the f32 contraction error
            q = 2.2 * 2.0**-9 + pts.shape[1] * 2.0**-22
        else:
            q = q_f32
        halo = spill.chord_halo(cfg.eps, q, dim=int(pts.shape[1]))
        # Zero-norm rows are sim-0 (cos_dist exactly 1) to everything:
        # inside the spill tree each would be equidistant to every pivot
        # and get copied into every cell at every level. Whenever even
        # the quantized kernel cannot accept a zero-to-nonzero pair
        # (eps + q < 1), they are noise by fiat — run the pipeline on
        # the nonzero rows alone and scatter the results back. Norms in
        # f64 from the original data: an f32 norm would underflow tiny
        # rows into false zeros (the kernel normalizes in higher
        # precision and would find their neighbors).
        # Same-dataset fast path: a resident-cache hit (identity +
        # full-coverage checksum) proves the data unchanged since a
        # prior call that PASSED the zero-norm screen and built both
        # the host unit rows and the device payload — skip the ~2.5 s
        # of re-normalization (einsum norms + f32 copy + divide) along
        # with the re-upload. eps/min_points may differ (halo above is
        # config-derived); unit depends on the data alone.
        cached, fp_hint = (
            _resident_payload_lookup(pts)
            if resident_mode
            else (None, None)
        )
        if cached is not None and cached[2] and (cfg.eps + q) < 1.0:
            # the cached data carries zero-norm rows and THIS config's
            # screen applies (the entry was built under a config whose
            # eps + q >= 1 bypassed it): take the slow path so the
            # screen routes them to noise
            cached = None
        # f64 accumulation without materializing an f64 copy: einsum
        # upcasts per buffer block, so tiny f32 rows don't underflow
        # into false zeros
        norms64 = (
            None
            if cached is not None
            else np.sqrt(
                np.einsum("ij,ij->i", pts, pts, dtype=np.float64)
            )
        )
        zeros = norms64 == 0.0 if norms64 is not None else None
        if zeros is not None and zeros.any() and (cfg.eps + q) < 1.0:
            # zeros.all() included: the nonzero sub-run is then empty and
            # every row is noise by fiat — the all-constant-zero input
            # otherwise runs the full spill tree on all-equidistant
            # (chord sqrt(2)) unit vectors, its worst case.
            # KNOWN LIMITATION: pts[~zeros] is a fresh temp each call,
            # so datasets WITH zero-norm rows never benefit from the
            # resident cache under this (common) screened config — the
            # one-entry eviction policy cannot hold a parent entry and
            # the sub-run's entry simultaneously. Sweep workloads
            # should drop zero rows once, upstream, and pass the same
            # filtered array across calls.
            sub = train_arrays(
                pts[~zeros], cfg, mesh=mesh, checkpoint_dir=checkpoint_dir
            )
            clusters = np.zeros(n, dtype=np.int32)
            flags = np.full(n, NOISE, dtype=np.int8)
            nzi = np.flatnonzero(~zeros)
            clusters[nzi] = sub.clusters
            flags[nzi] = sub.flags
            stats = dict(sub.stats)
            # sub-run stats describe the nonzero subset; rescale the
            # instance ratio to the full N and record the zero-norm rows
            # routed to noise so the diagnostics stay consistent
            if "duplication_factor" in stats:
                stats["duplication_factor"] = float(
                    stats["duplication_factor"] * (n - int(zeros.sum())) / n
                )
            stats["n_points"] = n
            stats["n_zero_norm_noise"] = int(zeros.sum())
            return TrainOutput(
                clusters, flags, sub.partitions, sub.n_clusters, stats
            )
        # hot/cold accounting: a HIT skips the ~1 GB payload re-upload
        # (and the ~2.5 s re-normalization) — the difference behind the
        # 5-60 s cosine capture swing VERDICT r5 flagged; bench.py tags
        # every timed rep with this
        if resident_mode:
            if cached is not None:
                obs.count("resident_cache.hits")
                obs.event("resident_cache.hit", n=int(n))
            else:
                obs.count("resident_cache.misses")
                obs.event("resident_cache.miss", n=int(n))
        if cached is not None:
            unit, resident_ops = cached[0], cached[1]
        else:
            # normalize straight into f32 (the spill pass's working
            # dtype): a 10M x 512 f64 intermediate would triple peak
            # host memory. copy=True: pts may alias the CALLER'S array
            # (f32 inputs are passed through un-copied) and the
            # in-place divide below must never touch it
            unit = pts.astype(np.float32, copy=True)
            unit /= np.maximum(
                np.linalg.norm(unit, axis=1), np.float32(1e-30)
            )[:, None]
            if resident_mode:
                try:
                    from dbscan_tpu.parallel import spill_device as _sdev

                    resident_ops = _resident_payload_cached(
                        pts, unit, _sdev,
                        has_zeros=bool(zeros.any()), fp=fp_hint,
                    )
                except Exception as e:  # noqa: BLE001 — host fallback
                    logger.warning(
                        "cosine resident payload unavailable (%s)", e
                    )
                    resident_ops = None
                    # the run measures in exact f32 after all — drop
                    # the bf16 widening so the halo (and its
                    # duplication) match the path actually taken
                    if cfg.precision.value != "bf16":
                        q = q_f32
                        halo = spill.chord_halo(
                            cfg.eps, q, dim=int(pts.shape[1])
                        )
        if resident_ops is not None:
            # the CPU degradation path for resident-gather groups
            # rebuilds each partition's rows from the host unit copy
            resident_unit = unit
        rp = spill.spill_partition(
            unit, cfg.max_points_per_partition, halo,
            device_ops=resident_ops, info_out=spill_info,
        )
        _mark("spill_partition_s", t0)
        if rp[2]:
            # oversized unsplittable leaves fail fast, pre-packing —
            # leaf counts come straight from the partitioner's layout
            counts_rp = spill_info.get("counts")
            if counts_rp is None:
                counts_rp = np.bincount(rp[0], minlength=rp[2])
            cmax = int(counts_rp.max())
            _check_dense_width(
                binning._ladder_width(cmax, cfg.bucket_multiple), cmax
            )
    if not spatial and rp is None and not cfg.use_pallas:
        # single partition, dense engine: the whole dataset is one bucket
        _check_dense_width(binning._ladder_width(n, cfg.bucket_multiple), n)

    maxpp_eff = cfg.max_points_per_partition
    if spatial:
        # 1-2. cell histogram + spatial partitioning (driver-local metadata).
        t0 = time.perf_counter()
        cells, counts, cell_inv = geo.cell_histogram_int(grid_pts, cell)
        t0 = _mark("histogram_s", t0)
        maxpp_eff = _effective_maxpp(cfg, counts)
        parts = partitioner.partition_cells(cells, counts, maxpp_eff)
        _mark("partition_s", t0)
        rects_int = np.stack([r for r, _ in parts])
        logger.info("found %d partitions for %d points", len(parts), n)
        # 3. margins (grown by eps_spatial: eps plus the projection's
        # slack budget — equals eps exactly for euclidean runs).
        margins = binning.build_margins(rects_int, cell, eps_spatial)
    elif rp is not None:
        rects_int = None
        margins = None  # no rectangles in the spill-tree decomposition
    else:
        rects_int = None
        lo = pts[:, :2].min(axis=0)
        hi = pts[:, :2].max(axis=0)
        main = np.array([[lo[0], lo[1], hi[0], hi[1]]], dtype=np.float64)
        margins = binning.Margins(
            inner=geo.shrink(main, cfg.eps),
            main=main,
            outer=geo.shrink(main, -cfg.eps),
        )
    p_true = rp[2] if rp is not None else margins.main.shape[0]

    # 4. halo duplication + static bucketing.
    t0 = time.perf_counter()
    if rp is not None:
        part_ids, point_idx = rp[0], rp[1]  # spill tree already duplicated
    elif rects_int is not None:
        part_ids, point_idx = binning.duplicate_points_grid(
            grid_pts, cells, cell_inv, rects_int, margins.outer
        )
    else:
        part_ids, point_idx = binning.duplicate_points(pts, margins.outer)
    t0 = _mark("duplicate_s", t0)
    if cfg.precision.value == "f64" and not jax.config.jax_enable_x64:
        raise ValueError(
            "precision=F64 requires jax_enable_x64 (else buffers silently "
            "downcast to f32); enable it or use Precision.F32"
        )
    import ml_dtypes

    dtype = {
        "f32": np.float32,
        "f64": np.float64,
        "bf16": ml_dtypes.bfloat16,
    }[cfg.precision.value]
    if cfg.neighbor_backend == "banded" and cfg.precision.value == "bf16":
        raise ValueError(
            "neighbor_backend='banded' requires f32/f64: bf16 rounds d2 by "
            "~4e-3 relative — far past the fine grid's 1e-5 margins "
            "(binning.FINE_CELL_FACTOR) — breaking both the same-cell "
            "clique guarantee and the 5x5-window coverage of accepted "
            "pairs; use precision=F32 or the dense backend"
        )
    use_banded = (
        cfg.neighbor_backend != "dense"
        and kernel_metric == "euclidean"
        and cfg.precision.value != "bf16"
        and (
            kernel_cols.shape[1] == 2
            # spherical chord payload: requires the projection's reach
            # margin (latitude spans past ~49 degrees fail it and run the
            # dense kernel per partition — still spatially decomposed)
            or (sph is not None and sph.banded_ok)
        )
    )
    # use_pallas now rides the banded structure (ops/pallas_banded.py —
    # fixed two sweeps + host cell-CC, the round-2 verdict's fix for the
    # O(diameter) re-sweep loss); neighbor_backend="dense" keeps the
    # original streaming engine for force-dense expert runs.
    # Dispatch each group's device program the moment its buffers are
    # packed (on_group): the first groups' sweeps run while later groups
    # are still packing, pulling the device window forward under the
    # packer instead of serializing behind it.
    pending = []
    dispatch_spent = [0.0]
    # Pipelined pull engine (parallel/pipeline.py): D2H pulls + the host
    # finalize that consumes them run on a background worker, bounded by
    # DBSCAN_PULL_INFLIGHT/_BYTES, so transfers overlap host algebra and
    # remaining device dispatch. None under DBSCAN_PULL_PIPELINE=0 (every
    # serial code path below is then byte-for-byte the pre-pipeline one).
    # Multi-process runs get the COLLECTIVE-AWARE engine: jobs execute
    # inline at their (plan-deterministic) submission points — the
    # per-shard submission barrier that keeps every process's cross-host
    # pull sequence identical — so stats["pull"] / pull_overlap_ratio
    # exist per shard there too.
    pull_pipe = pipe_mod.get_engine()
    pull_snap = pull_pipe.totals() if pull_pipe is not None else None
    # DBSCAN_TIME_DEVICE=1: block synchronously on each banded phase-1
    # dispatch and accumulate the pure device-execution window into
    # timings["banded_p1_sync_s"]. This sacrifices pack/compute overlap
    # (do NOT enable on a timed run) but isolates the sweep-kernel time
    # the MFU accounting divides by — with async dispatch the device
    # window hides under host phases and cannot be attributed.
    time_device = bool(config_mod.env("DBSCAN_TIME_DEVICE"))
    sync_spent = [0.0]
    flops_spent = [0]
    bytes_spent = [0]
    # Dispatch backpressure: every queued-but-unexecuted program pins its
    # input buffers (points/mask/run tables, ~25 B per padded slot) in
    # HBM, so letting the packer run arbitrarily far ahead of the device
    # exhausts the 16 GB chip at ~300M slots (observed: the TPU worker
    # dies outright at 100M points, any maxpp). Track dispatched-not-yet-
    # retired slots and block on the OLDEST group's output once the
    # window exceeds the budget — the sliding window keeps pack/compute
    # overlap while bounding residency.
    inflight: list = []  # (slots, output leaf to block on)
    inflight_slots = [0]

    # Eager compact chunking (+ the resumable device phase): banded
    # groups accumulate into slot-budgeted chunks AS THEY PACK; when a
    # chunk fills, its postpass dispatches immediately and the PREVIOUS
    # chunk is pulled (one-chunk pipeline: that pull has the newer
    # chunk's phase-1 window executing behind it). Each pulled chunk is
    # a few dozen MB of final artifacts — with a checkpoint_dir they
    # persist at once, so a mid-device-phase worker death (observed on
    # the tunneled TPU after ~15-25 min of continuous work) costs at
    # most one chunk of recompute: the resumed run re-packs
    # (deterministic), skips dispatch for groups covered by saved
    # chunks, and picks up where the chunks stop. cell_layout needs only
    # per-group tables, so none of this waits for packing to finish.
    compact_on = use_banded and not config_mod.env("DBSCAN_NO_COMPACT")
    if campaign is not None:
        if not (use_banded and compact_on):
            raise ValueError(
                "campaign chunk leases require the banded compact path "
                "(the p1 chunk checkpoints ARE the lease currency): got "
                f"metric={cfg.metric!r} "
                f"neighbor_backend={cfg.neighbor_backend!r} "
                f"compact={'on' if compact_on else 'off'}"
            )
        if campaign.tier not in ("device", "cpu"):
            raise ValueError(
                f"campaign tier must be 'device' or 'cpu', got "
                f"{campaign.tier!r}"
            )
        # leased chunks pull serially at their own completion (below) —
        # the campaign's parallelism is across legs, not inside one, and
        # a serial pull keeps the save-then-heartbeat ordering the lease
        # kill/steal accounting depends on
        pull_pipe = None
        pull_snap = None
    if compact_on:
        from dbscan_tpu.ops.banded import (
            banded_postpass,
            compiled_cellcc_unpack,
            gather_flat,
        )
    # Device-resident cellcc finalize (ROADMAP item 3): per-chunk
    # `cellcc.unpack` folds the packed core/scan slabs into per-cell
    # partials AS CHUNKS FLUSH (riding the packing window), then ONE
    # fused `cellcc.cc` dispatch at the tail runs the cell
    # connected-components union + border algebra on device, so only
    # the final valid-prefix [V] labels cross the link — the host
    # unpackbits/flatnonzero/scipy pass (20+ s of cellcc_pull_core_s at
    # 3M+ points) disappears. Host path stays the parity oracle under
    # DBSCAN_CELLCC_DEVICE=0, and structurally under checkpoints (saved
    # chunks ARE the pulled host artifacts), multi-process (pull order
    # is a collective contract), and DBSCAN_EAGER_PULL (serial-pull
    # resilience mode). `cpad` (ladder-padded cell count + sentinel
    # row) lands via bucketize_banded's on_meta callback BEFORE any
    # chunk flushes.
    cellcc_dev = {
        "on": (
            compact_on
            and bool(config_mod.env("DBSCAN_CELLCC_DEVICE"))
            and ckpt_fp is None
            and not mesh_mod.multiprocess()
            and not config_mod.env("DBSCAN_EAGER_PULL")
            # a pull-site fault clause targets the per-chunk pull jobs
            # (their supervised wrap + ordinal stream): honor it on the
            # host path rather than silently consuming no pull ordinals
            and not faults.pull_site_active()
        ),
        "cpad": 0,
        "iters": 0,
        "slots": 0,  # staged device-finalize slots (HBM residency guard)
        # fused Pallas unpack+fold+propagate (ops/pallas_banded.py):
        # resolved ONCE per run so every chunk stages the same shape —
        # a mid-run flip would mix lab0-bearing and bare records and
        # make the counted sweeps chunk-mix-dependent
        "fused": False,
        "wintab_dev": None,  # shared padded window table (fused + cc)
        "meta": None,  # CellGraphMeta (wintab source)
        # propagation mode of the tail cc, resolved per run for the
        # same reason (it keys the compiled cc trace)
        "prop_mode": None,
    }
    if cellcc_dev["on"]:
        from dbscan_tpu.ops import pallas_banded as pallas_cellcc
        from dbscan_tpu.ops import propagation as prop_mod

        cellcc_dev["fused"] = pallas_cellcc.fused_mode()
        cellcc_dev["prop_mode"] = prop_mod.prop_mode()
    # Staged-residency cap: unlike the host path (whose _pull_record
    # pops each chunk's combo/bits after its pull), the device finalize
    # keeps every chunk's packed buffers PLUS ~13 B/slot of staged
    # cells/folds/core/bits resident until the tail CC dispatch. The
    # cap bounds that at ~13 B * DBSCAN_CELLCC_DEVICE_SLOTS; a run
    # whose chunks exceed it degrades the finalize to the host oracle
    # MID-RUN (staged partials are dropped so their HBM frees, and the
    # already-flushed chunks re-enter the normal pipelined pulls) —
    # labels identical either way, only the finalize locus moves.
    _CELLCC_DEVICE_SLOTS = int(config_mod.env("DBSCAN_CELLCC_DEVICE_SLOTS"))

    def _cellcc_degrade_residency():
        cellcc_dev["on"] = False
        logger.warning(
            "device cellcc finalize: staged slots would exceed "
            "DBSCAN_CELLCC_DEVICE_SLOTS=%d — degrading the finalize to "
            "the host path (labels unchanged)",
            _CELLCC_DEVICE_SLOTS,
        )
        for r in eager["records"]:
            r.pop("dev", None)  # free the staged partials' HBM
            # restore the PR-5 overlap for the chunks already flushed:
            # they never got a pull job (nor an async copy) in device
            # mode; serial runs at least start the D2H moving so the
            # tail's back-to-back _pull_record calls find the combos
            # already in flight
            if "combo_dev" not in r or "pull_job" in r:
                continue
            if pull_pipe is not None and not eager.get("aborting"):
                _submit_pull(r)
            elif not mesh_mod.multiprocess():
                r["combo_dev"].copy_to_host_async()

    def _on_cellmeta(meta):
        if meta.n_cells == 0:
            cellcc_dev["on"] = False
            return
        cellcc_dev["meta"] = meta
        cellcc_dev["cpad"] = binning._ratchet(
            getattr(cfg, "shape_floors", None),
            "cellcc_cells",
            binning._ladder_width(meta.n_cells + 1, 4096),
        )

    def _wintab_dev():
        """The padded [cpad, 25] window table, uploaded ONCE per run
        and shared by the per-chunk fused dispatches and the tail cc
        (the fused path needs it at flush time for the folded first
        sweep; the split path only at the tail)."""
        if cellcc_dev["wintab_dev"] is None:
            meta = cellcc_dev["meta"]
            wt = np.full(
                (cellcc_dev["cpad"], binning.BANDED_WIN), -1, np.int32
            )
            wt[: meta.n_cells] = meta.wintab
            cellcc_dev["wintab_dev"] = mesh_mod.replicate_host_array(wt)
        return cellcc_dev["wintab_dev"]
    eager = {
        "cur": [],  # pending indices of the open chunk's banded groups
        "cur_slots": 0,
        "cur_ord0": 0,  # CANONICAL ordinal of the open chunk's first group
        "records": [],  # per-chunk dicts (live or checkpoint-loaded)
        "pull_spent": 0.0,
    }
    p1_loaded: list = []
    p1_exp: list = []  # (chunk idx, (P, B, slab)) per CANONICAL ordinal
    # campaign legs never ADOPT saved chunks (they only produce them):
    # the lease queue already excludes completed chunks, and the
    # consecutive-prefix loader cannot represent the arbitrary subsets
    # concurrent legs bank — the finalize run (no CampaignLeg) is where
    # the full prefix loads and merges
    if compact_on and ckpt_fp is not None and campaign is None:
        from dbscan_tpu.parallel import checkpoint as _ckpt_p1

        p1_loaded = _ckpt_p1.load_p1_chunks(
            checkpoint_dir, ckpt_fp, budget=_COMPACT_CHUNK_SLOTS
        )
        for lci, lc in enumerate(p1_loaded):
            for row in lc["shapes"]:
                p1_exp.append((lci, tuple(int(v) for v in row)))
    # Pre-seed one placeholder record per saved chunk. Covered groups are
    # routed here by CANONICAL ordinal as they arrive — which, on a
    # resumed run, is LAST: binning emits a rotation of its canonical
    # plan (resume_prefix) so uncovered groups reach the device within
    # seconds of the fine-grid pass instead of after minutes of re-pack.
    # A placeholder completes (checkpoint arrays adopted, or divergence
    # recomputed) once all its groups have arrived.
    for lci, lc in enumerate(p1_loaded):
        eager["records"].append(
            {
                "ch": [],
                "ci": lci,
                "pending_loaded": lc,
                "expect": len(lc["shapes"]),
                "ord0": next(
                    k for k, (c, _s) in enumerate(p1_exp) if c == lci
                ),
            }
        )

    # Campaign chunk-lease state (campaign is not None): the plan map
    # (ordinal -> chunk index, per-chunk group count / first ordinal,
    # filled by _on_plan BEFORE any group emits), the per-chunk
    # accumulation of leased groups' pending indices, and the completed
    # chunk list the partial exit + kill drill read.
    camp_plan: dict = {"chunk_of": [], "count": {}, "ord0": {}}
    camp_acc: dict = {}
    camp_done: list = []

    def _chunk_sig(ch, ord0):
        # salted with the chunk's starting banded ordinal: shapes are
        # ladder-quantized (repeats are common), so a budget change
        # shifting chunk boundaries could otherwise re-form a
        # shape-identical chunk over DIFFERENT groups and silently apply
        # the wrong saved results
        h = hashlib.sha256()
        h.update(f"ord{ord0}|".encode())
        for i in ch:
            g = pending[i][0]
            h.update(
                f"{g.points.shape}|{int(g.banded.slab)}|".encode()
            )
        return h.hexdigest()

    def _redispatch(i):
        """Re-dispatch a group whose checkpoint skip turned out invalid
        (chunk composition diverged — e.g. a changed chunk budget)."""
        g = pending[i][0]
        out = _dispatch_banded_p1(g, cfg, mesh, kernel_eps)
        flops_spent[0] += _group_flops(g)
        bytes_spent[0] += _group_bytes(g)
        pending[i] = (g, out)
        ts = time.perf_counter()
        jax.block_until_ready(out[0])
        if time_device:  # keep the MFU ratio honest on diverged resumes
            sync_spent[0] += time.perf_counter() - ts

    def _pull_record(rec, account=True):
        """Block on a live chunk's postpass, compute its border gather,
        and (with a checkpoint_dir) persist the artifacts. The record is
        NOT mutated until every pull succeeded, so a failed attempt can
        re-enter (faults.supervised retry on the pipeline worker, or the
        abort path's serial re-walk) and re-run from the top.
        ``account=False`` on the pipeline worker: the main thread charges
        only the time it actually BLOCKED to ``pull_spent`` — the
        timings algebra (dispatch_s/cellcc_pull_core_s) subtracts pull
        stalls, and a pull that overlapped other work stalled nothing."""
        if "combo_host" in rec or "pending_loaded" in rec or "dropped" in rec:
            return  # done, placeholder still collecting, or re-chunked
        if "combo_dev" not in rec:
            return  # a prior pull died mid-record (abort-path re-walk)
        tp = time.perf_counter()
        layout = rec["layout"]
        total = layout["total"]
        combo_host = mesh_mod.pull_to_host(rec["combo_dev"])
        core_ch, bpos = cellgraph.unpack_combo(combo_host, layout)
        bb_dev = obs_compile.tracked_call(
            "cellcc.gather",
            gather_flat,
            rec["bits_flat"],
            mesh_mod.replicate_host_array(
                _pad_idx(bpos, getattr(cfg, "shape_floors", None))
            ),
        )
        bbits = mesh_mod.pull_to_host(bb_dev)[: len(bpos)]
        rec["combo_host"] = combo_host
        rec["core_ch"] = core_ch
        rec["bpos"] = bpos
        rec["bbits"] = bbits
        rec.pop("combo_dev")
        rec.pop("bits_flat")
        if account:
            eager["pull_spent"] += time.perf_counter() - tp
        obs.count("checkpoint.chunk_pulls")
        obs.add_span(
            "compact.pull_chunk",
            tp,
            time.perf_counter(),
            chunk=int(rec["ci"]),
            slots=int(total),
        )
        if ckpt_fp is not None:
            from dbscan_tpu.parallel import checkpoint as _ckpt_p1

            shapes = np.array(
                [
                    (
                        pending[i][0].points.shape[0],
                        pending[i][0].points.shape[1],
                        int(pending[i][0].banded.slab),
                    )
                    for i in rec["ch"]
                ],
                dtype=np.int64,
            )
            _ckpt_p1.save_p1_chunk(
                checkpoint_dir,
                ckpt_fp,
                rec["ci"],
                rec["sig"],
                shapes,
                {"combo": combo_host, "bbits": bbits},
                budget=_COMPACT_CHUNK_SLOTS,
            )

    def _run_postpass(rec):
        """Dispatch a record's compact postpass from its (now complete)
        groups, redispatching any checkpoint-skipped ones first."""
        ch = rec["ch"]
        for i in ch:
            if pending[i][1] is None:
                _redispatch(i)
        layout = cellgraph.cell_layout(rec["groups"])
        or_idx = _pad_idx(layout["or_pos"])
        combo_dev, bits_flat = obs_compile.tracked_call(
            "cellcc.postpass",
            banded_postpass,
            tuple(pending[i][1][0] for i in ch),
            tuple(pending[i][1][1] for i in ch),
            tuple(
                mesh_mod.replicate_host_array(f)
                for f in layout["segflags"]
            ),
            mesh_mod.replicate_host_array(or_idx),
        )
        if (
            not mesh_mod.multiprocess()
            and pull_pipe is None
            and not cellcc_dev["on"]
        ):
            # local-shard async copy; cross-host pulls gather instead.
            # Pipelined runs defer this to the job's start hook so the
            # DBSCAN_PULL_INFLIGHT_BYTES budget bounds how many chunks
            # are host-materialized at once; device-finalize runs never
            # pull the combo at all unless they degrade
            combo_dev.copy_to_host_async()
        rec["layout"] = layout
        rec["combo_dev"] = combo_dev
        rec["bits_flat"] = bits_flat
        if cellcc_dev["on"] and (
            cellcc_dev["slots"] + layout["total"] > _CELLCC_DEVICE_SLOTS
        ):
            _cellcc_degrade_residency()
        if cellcc_dev["on"]:
            # stage the chunk's device finalize inputs while later
            # groups still pack: upload the flat cell/fold metadata and
            # fold the packed slabs into per-cell partials ON DEVICE.
            # The or-gid vector pads to the SAME ladder as or_idx above
            # (padding scatters to the sentinel row, discarded); the
            # combo/bits handles stay in the record untouched, so a
            # later degrade to the host oracle pulls them as if this
            # staging never happened.
            cellcc_dev["slots"] += layout["total"]
            cpad = cellcc_dev["cpad"]
            cell_h, fold_h = cellgraph.device_chunk_arrays(
                rec["groups"], cpad - 1
            )
            gid_pos = cellgraph.or_gid_positions(layout)
            gid_pad = np.full(len(or_idx), cpad - 1, np.int32)
            gid_pad[: len(gid_pos)] = gid_pos
            cell_d = mesh_mod.replicate_host_array(cell_h)
            fold_d = mesh_mod.replicate_host_array(fold_h)
            if cellcc_dev["fused"]:
                # fused Pallas unpack+fold+propagate: the unpack/cc
                # pair's per-chunk half becomes ONE cellcc.fused
                # dispatch that also folds the first propagation sweep
                # (lab0); the tail cc then starts one sweep warm
                # (compiled_cellcc_cc warm=True)
                from dbscan_tpu.ops.pallas_banded import (
                    compiled_cellcc_fused,
                )

                core_d, cellor_d, cellfold_d, lab0_d = (
                    obs_compile.tracked_call(
                        "cellcc.fused",
                        compiled_cellcc_fused(cpad),
                        combo_dev,
                        cell_d,
                        fold_d,
                        mesh_mod.replicate_host_array(gid_pad),
                        _wintab_dev(),
                    )
                )
            else:
                core_d, cellor_d, cellfold_d = obs_compile.tracked_call(
                    "cellcc.unpack",
                    compiled_cellcc_unpack(cpad),
                    combo_dev,
                    cell_d,
                    fold_d,
                    mesh_mod.replicate_host_array(gid_pad),
                )
                lab0_d = None
            rec["dev"] = {
                "core": core_d,
                "cellor": cellor_d,
                "cellfold": cellfold_d,
                "cells": cell_d,
                "folds": fold_d,
                "bits": bits_flat,
            }
            if lab0_d is not None:
                rec["dev"]["lab0"] = lab0_d

    def _submit_pull(rec):
        """Hand a freshly-flushed chunk's pull + host finalize to the
        pipeline worker. When the active fault spec names the ``pull``
        site, the job runs under faults.supervised so retry/halving
        happens ON the worker — a failed pull re-enters the pipeline
        job, not the raw call (the record is untouched until success,
        see _pull_record)."""
        layout = rec["layout"]
        combo_dev = rec["combo_dev"]
        # host-side footprint estimate: the packed combo buffer plus the
        # unpacked core bools plus a border-gather worst case
        hint = int(getattr(combo_dev, "nbytes", 0)) + 2 * int(
            layout["total"]
        )
        if faults.pull_site_active():
            def work(rec=rec):
                faults.supervised(
                    faults.SITE_PULL,
                    lambda _b: _pull_record(rec, account=False),
                    label=f"chunk {rec['ci']}",
                )
        else:
            def work(rec=rec):
                _pull_record(rec, account=False)
        rec["pull_job"] = pull_pipe.submit(
            work,
            on_start=combo_dev.copy_to_host_async,
            bytes_hint=hint,
            label=f"chunk{rec['ci']}",
        )

    def _consume_pull(rec):
        """Settle a record at a consuming site: block on its pipeline
        job when one exists (charging only the blocked wall to
        pull_spent — that is the stall the timings algebra subtracts),
        re-raising any worker fault HERE so _abort_guard banks earlier
        chunks' artifacts exactly as on the serial path; then the
        serial _pull_record covers every non-pipelined case (no-op when
        the job already landed the artifacts)."""
        job = rec.pop("pull_job", None)
        if job is not None:
            tw = time.perf_counter()
            try:
                pull_pipe.settle(job)
            finally:
                eager["pull_spent"] += time.perf_counter() - tw
        _pull_record(rec)

    def _complete_placeholder(rec):
        """All of a saved chunk's groups have arrived: verify the ordinal-
        salted composition signature and adopt the checkpointed artifacts.
        On divergence (changed plan slipping past the fingerprint) the
        saved composition is STALE: its stale file is invalidated so
        future legs' prefix load truncates there, and its groups re-enter
        the normal budgeted accumulation — reusing the stale composition
        for a recompute could concatenate past the chunk slot cap (the
        2^31-byte per-buffer kill) and would hold every diverged chunk's
        postpass buffers resident at once instead of the one-behind
        pipeline."""
        lc = rec.pop("pending_loaded")
        rec.pop("expect", None)
        rec["groups"] = [pending[i][0] for i in rec["ch"]]
        rec["sig"] = _chunk_sig(rec["ch"], rec["ord0"])
        covered = all(pending[i][1] is None for i in rec["ch"])
        if covered and lc["sig"] == rec["sig"]:
            rec["combo_host"] = lc["arrays"]["combo"]
            rec["bbits"] = lc["arrays"]["bbits"]
            return
        rec["dropped"] = True
        if ckpt_fp is not None:
            from dbscan_tpu.parallel import checkpoint as _ckpt_p1

            _ckpt_p1.invalidate_p1_chunk(checkpoint_dir, rec["ci"])
        for i in rec["ch"]:
            g_i = pending[i][0]
            sz_g = g_i.mask.shape[0] * g_i.mask.shape[1]
            if (
                eager["cur"]
                and eager["cur_slots"] + sz_g > _COMPACT_CHUNK_SLOTS
            ):
                _flush_chunk()
            if not eager["cur"]:
                eager["cur_ord0"] = g_i.ordinal
            eager["cur"].append(i)
            eager["cur_slots"] += sz_g

    def _flush_chunk():
        ch = eager["cur"]
        if not ch:
            return
        eager["cur"] = []
        eager["cur_slots"] = 0
        ci = len(eager["records"])
        sig = _chunk_sig(ch, eager.get("cur_ord0", 0))
        ch_groups = [pending[i][0] for i in ch]
        rec = {"ch": ch, "ci": ci, "sig": sig, "groups": ch_groups}
        obs.count("checkpoint.chunk_flushes")
        with obs.span(
            "compact.flush_chunk", chunk=int(ci), groups=len(ch)
        ):
            _run_postpass(rec)
        eager["records"].append(rec)
        # DBSCAN_EAGER_PULL=1 pulls each chunk serially at its own flush
        # — resilience over overlap, for retry loops on a worker that
        # keeps dying before a delayed pull lands. Multi-process: forced
        # OFF — pulls issue cross-process collectives, and an env var
        # set on only some hosts would desynchronize the collective
        # order (the checkpointing it serves is single-process anyway).
        # Otherwise the pull engine takes the chunk: its D2H + host
        # finalize run on the worker, bounded-depth ahead, overlapping
        # the remaining dispatch. The abort path cancels not-yet-started
        # jobs and settles serially (_abort_flush), so submits stop once
        # an abort began. With no engine, the serial one-behind pipeline
        # (pull chunk i-1 while chunk i's phase-1 window executes).
        if cellcc_dev["on"]:
            # device finalize: nothing to pull per chunk — the unpack
            # partials staged in _run_postpass wait for the tail's one
            # fused cellcc.cc dispatch, whose [V]-label pull is the
            # only D2H of the whole finalize
            pass
        elif (
            config_mod.env("DBSCAN_EAGER_PULL")
            and not mesh_mod.multiprocess()
        ):
            _pull_record(rec)
        elif pull_pipe is not None and not eager.get("aborting"):
            _submit_pull(rec)
        elif len(eager["records"]) >= 2:
            _pull_record(eager["records"][-2])

    def _camp_complete_chunk(ci, ch):
        """All of leased chunk ``ci``'s groups have arrived: run its
        postpass, pull the artifacts serially, and persist them at the
        PLAN chunk index (the composition signature is computed exactly
        as a sequential run would, so the finalize run adopts the file
        without redispatch). Fires the lease heartbeat, then the
        deterministic kill drill when armed."""
        rec = {
            "ch": ch,
            "ci": ci,
            "sig": _chunk_sig(ch, camp_plan["ord0"][ci]),
            "groups": [pending[i][0] for i in ch],
        }
        obs.count("checkpoint.chunk_flushes")
        with obs.span(
            "compact.flush_chunk", chunk=int(ci), groups=len(ch)
        ):
            _run_postpass(rec)
        _pull_record(rec)
        # the abort path's serial re-walk must see this record (a
        # no-op: artifacts already pulled + saved)
        eager["records"].append(rec)
        camp_done.append(int(ci))
        if campaign.on_chunk is not None:
            campaign.on_chunk(int(ci))
        if campaign.kill_after and len(camp_done) >= campaign.kill_after:
            # deterministic worker-kill drill: die AFTER banking this
            # chunk, through the same FatalDeviceFault/abort-guard path
            # a real mid-leg death takes (note_abort + flightrec dump)
            raise faults.FatalDeviceFault(
                faults.SITE_CAMPAIGN,
                campaign.kill_ordinal,
                1,
                faults.FaultInjected(
                    faults.SITE_CAMPAIGN,
                    campaign.kill_ordinal,
                    faults.TRANSIENT,
                ),
            )

    def _abort_flush(site, ordinal, msg):
        """A device fault with no degradation path is about to abort the
        run. Before it propagates, bank a restart point at the LAST
        COMPLETED GROUP: close the open compact chunk and pull+persist
        every live chunk, so the resumed leg restarts after the last
        healthy group rather than at the last chunk boundary.
        Best-effort — the original fault re-raises regardless (and if
        the worker is truly dead, the inner flush fails too; whatever
        chunks were already pulled stay persisted)."""
        if not (compact_on and ckpt_fp is not None):
            return
        # record the abort FIRST (host-only, survives a dead worker),
        # then best-effort flush — on a truly dead backend the flush's
        # own device ops fail and only the already-pulled chunks remain
        try:
            from dbscan_tpu.parallel import checkpoint as _ckpt_ab

            _ckpt_ab.note_abort(
                checkpoint_dir,
                aborted_site=site,
                aborted_ordinal=int(ordinal),
                abort_error=msg[:200],
            )
        except Exception:  # noqa: BLE001 — the fault itself must win
            logger.exception("abort-path progress note failed")
        try:
            # stop feeding the pipeline and settle serially: cancelled
            # jobs never ran, so their records are untouched and the
            # serial _pull_record below re-pulls them; completed jobs
            # already banked (and checkpointed) their artifacts on the
            # worker — exactly the "earlier chunks' work is never
            # wasted" guarantee the serial abort path gives
            eager["aborting"] = True
            if pull_pipe is not None:
                pull_pipe.quiesce()
            _flush_chunk()
            for rec in eager["records"]:
                job = rec.pop("pull_job", None)
                if job is not None:
                    try:
                        pull_pipe.wait(job)
                    except Exception:  # noqa: BLE001 — settle the rest
                        logger.exception(
                            "abort-path pipelined pull failed"
                        )
                _pull_record(rec)
        except Exception:  # noqa: BLE001 — the fault itself must win
            logger.exception(
                "abort-path chunk flush failed (restart point may be "
                "one chunk stale)"
            )

    @contextlib.contextmanager
    def _abort_guard():
        """Abort-path coverage for a slice of the device phase. Two
        fault shapes arrive here: a retries-exhausted supervised
        dispatch raises faults.FatalDeviceFault at its dispatch site,
        while a REAL async device fault normally surfaces at a
        consuming pull (_pull_record / the tail flush) as a raw
        device-runtime error — jax dispatch is asynchronous, so the
        dispatch-site wrapper cannot see it. Either way, bank a
        restart point before the fault propagates; non-device errors
        (faults.classify -> None) pass through untouched."""
        try:
            yield
        except faults.FatalDeviceFault as e:
            _halt_pipeline()
            _abort_flush(e.site, e.ordinal, str(e))
            # postmortem AFTER the flush: the ring now also holds the
            # abort-path spans (quiesce, banked-chunk pulls), so the
            # dump shows what was saved, not just what died
            obs_flight.dump_on_fault(e.site, e.ordinal, str(e))
            raise
        except Exception as e:  # noqa: BLE001 — classify() filters
            if faults.classify(e) is None:
                raise
            _halt_pipeline()
            _abort_flush("pull", -1, f"{type(e).__name__}: {e}")
            # async device faults surface here (a consuming pull), never
            # through faults.supervised — this is their ONLY dump site
            obs_flight.dump_on_fault("pull", -1, f"{type(e).__name__}: {e}")
            raise

    def _halt_pipeline():
        """A device fault is about to abort the run: stop feeding the
        pull engine and settle its in-flight job, whether or not a
        checkpoint_dir exists (the process-global engine must not carry
        this run's jobs into the next one). Cancelled jobs leave their
        records untouched; _abort_flush's serial re-walk covers them."""
        eager["aborting"] = True
        if pull_pipe is not None:
            try:
                pull_pipe.quiesce()
            except Exception:  # noqa: BLE001 — the fault itself must win
                logger.exception("pull-pipeline quiesce failed")

    def _on_group(g):
        td = time.perf_counter()
        if g.banded is None and campaign is not None:
            # dense small-bucket groups are not chunk currency: the
            # finalize run computes them — a partial leg's result would
            # be discarded at the early exit anyway
            out = None
        elif g.banded is None:
            out = _dispatch_partitions(
                g, cfg, mesh, kernel_eps, kernel_metric,
                resident_x=(
                    resident_ops.x
                    if resident_ops is not None
                    else None
                ),
                resident_unit=resident_unit,
            )
        elif compact_on and campaign is not None:
            k = g.ordinal  # CANONICAL ordinal (no rotation: no adoption)
            ci = (
                camp_plan["chunk_of"][k]
                if k is not None and k < len(camp_plan["chunk_of"])
                else None
            )
            if ci is None or ci not in campaign.chunks:
                out = None  # chunk not leased by this leg: skip entirely
            elif campaign.tier == "cpu":
                # degraded-tier lease: the whole leg runs the per-group
                # CPU degradation kernel (same algebra as the device
                # sweep — labels unchanged, faults.py contract)
                out = _cpu_dispatch_banded_p1(g, cfg, mesh, kernel_eps)
            else:
                out = _dispatch_banded_p1(g, cfg, mesh, kernel_eps)
        elif compact_on:
            k = g.ordinal  # CANONICAL ordinal (arrival may be rotated)
            exp = (
                p1_exp[k] if k is not None and k < len(p1_exp) else None
            )
            shape = (
                g.points.shape[0],
                g.points.shape[1],
                int(g.banded.slab),
            )
            if exp is not None and exp[1] == shape:
                out = None  # covered by a saved chunk: skip the device
            else:
                out = _dispatch_banded_p1(g, cfg, mesh, kernel_eps)
        else:
            out = _dispatch_banded_p1(g, cfg, mesh, kernel_eps)
        if g.banded is not None and out is not None:
            # sweep-FLOP accounting covers DISPATCHED groups only — a
            # checkpoint-covered skip ran nothing, and counting it would
            # overstate the MFU figure on resumed runs
            flops_spent[0] += _group_flops(g)
            bytes_spent[0] += _group_bytes(g)
        if time_device and g.banded is not None and out is not None:
            ts = time.perf_counter()
            jax.block_until_ready(out[0])
            sync_spent[0] += time.perf_counter() - ts
        pending.append((g, out))
        if out is not None:
            sz = g.mask.shape[0] * g.mask.shape[1]
            inflight.append((sz, out[0]))
            inflight_slots[0] += sz
            while len(inflight) > 1 and inflight_slots[0] > _INFLIGHT_SLOTS:
                osz, oout = inflight.pop(0)
                jax.block_until_ready(oout)
                inflight_slots[0] -= osz
        if g.banded is not None and compact_on and campaign is not None:
            if out is not None:
                if campaign.on_progress is not None:
                    # per-group heartbeat: the lease is alive even when
                    # its first CHUNK is still minutes away
                    campaign.on_progress()
                ci = camp_plan["chunk_of"][g.ordinal]
                acc = camp_acc.setdefault(ci, [])
                acc.append(len(pending) - 1)
                if len(acc) == camp_plan["count"][ci]:
                    _camp_complete_chunk(ci, acc)
        elif g.banded is not None and compact_on:
            k = g.ordinal
            if k is not None and k < len(p1_exp):
                # belongs to a saved chunk's composition (even on a shape
                # mismatch — the signature check at completion decides
                # adopt-vs-recompute): route to its placeholder record
                rec = eager["records"][p1_exp[k][0]]
                rec["ch"].append(len(pending) - 1)
                if len(rec["ch"]) == rec["expect"]:
                    _complete_placeholder(rec)
            else:
                sz_g = g.mask.shape[0] * g.mask.shape[1]
                # close the open chunk BEFORE an overflowing group joins:
                # the cap bounds the chunk's concatenated device buffers,
                # so a chunk may only exceed it when a SINGLE group does
                if (
                    eager["cur"]
                    and eager["cur_slots"] + sz_g > _COMPACT_CHUNK_SLOTS
                ):
                    _flush_chunk()
                if not eager["cur"]:
                    eager["cur_ord0"] = k
                eager["cur"].append(len(pending) - 1)
                eager["cur_slots"] += sz_g
        dispatch_spent[0] += time.perf_counter() - td

    def _on_plan(entries):
        """Mirror _flush_chunk's accumulation over the canonical plan to
        pre-compute how many chunk checkpoints the full run needs, and
        persist it (progress.json) so a retry-resume harness can report
        chunks_done/chunks_total even when every leg dies mid-device-
        phase. Exact, not an estimate: saved chunks were formed by this
        same rule in canonical order, so a resumed leg's new chunks pick
        up at the same boundaries."""
        total = 0
        chunks = 0
        cur = 0
        for k, (p_pad, b) in enumerate(entries):
            sz = p_pad * b
            total += sz
            if cur and cur + sz > _COMPACT_CHUNK_SLOTS:
                chunks += 1
                cur = 0
            cur += sz
            # ordinal -> plan chunk index (campaign chunk leases): group
            # k lands in the chunk open when it arrives — exactly the
            # record _flush_chunk would have put it in
            camp_plan["chunk_of"].append(chunks)
            camp_plan["count"][chunks] = camp_plan["count"].get(chunks, 0) + 1
            camp_plan["ord0"].setdefault(chunks, k)
        if cur:
            chunks += 1
        camp_plan["chunks_total"] = chunks
        from dbscan_tpu.parallel import checkpoint as _ckpt_p1

        _ckpt_p1.write_progress(
            checkpoint_dir,
            chunks_total=chunks,
            planned_groups=len(entries),
            planned_slots=total,
            chunk_budget=_COMPACT_CHUNK_SLOTS,
        )

    cellmeta = None
    # the guard spans every dispatch AND the pipelined pulls the
    # _on_group callbacks issue (_flush_chunk -> _pull_record): async
    # device faults surface at those pulls, not at the dispatch sites
    with _abort_guard():
        if use_banded:
            groups, max_b, cellmeta = binning.bucketize_banded(
                kernel_cols,
                part_ids,
                point_idx,
                n_parts=p_true,
                eps=grid_eps,
                outer=margins.outer,
                bucket_multiple=cfg.bucket_multiple,
                pad_parts_to=mesh_size(mesh),
                dtype=dtype,
                force=cfg.neighbor_backend == "banded",
                on_group=_on_group,
                grid_points=None if sph is None else sph.proj,
                pad_parts_ladder=cfg.static_partition_pad,
                # rotate emission so checkpoint-covered groups pack LAST
                # and uncovered device work starts within seconds (retry
                # legs on a dying worker must reach a NEW restart point
                # fast)
                resume_prefix=len(p1_exp),
                on_plan=(
                    _on_plan
                    if (compact_on and checkpoint_dir is not None)
                    else None
                ),
                on_meta=_on_cellmeta if cellcc_dev["on"] else None,
                shape_floors=getattr(cfg, "shape_floors", None),
            )
        else:
            groups, max_b = binning.bucketize_grouped(
                kernel_cols,
                part_ids,
                point_idx,
                n_parts=p_true,
                bucket_multiple=cfg.bucket_multiple,
                pad_parts_to=mesh_size(mesh),
                dtype=dtype,
                on_group=_on_group,
                pad_parts_ladder=cfg.static_partition_pad,
                shape_floors=getattr(cfg, "shape_floors", None),
                fill_payload=resident_ops is None,
            )
    timings["dispatch_s"] = round(
        dispatch_spent[0] - eager["pull_spent"] - sync_spent[0], 6
    )
    timings["bucketize_s"] = round(
        time.perf_counter() - t0 - dispatch_spent[0], 6
    )
    if time_device:
        timings["banded_p1_sync_s"] = round(sync_spent[0], 6)
    t0 = time.perf_counter()

    if campaign is not None:
        # chunk-leased partial leg: every leased chunk was pulled and
        # banked at its plan index as its last group arrived — there is
        # nothing to merge here. Return the partial accounting the
        # campaign worker reads; the finalize run (no CampaignLeg) loads
        # the fully-banked prefix and merges.
        missing = sorted(set(campaign.chunks) - set(camp_done))
        if missing:
            # plan/emission share one accumulation rule, so a leased
            # chunk that never completed means the lease was written
            # against a DIFFERENT plan (changed knobs/data slipping
            # past the campaign key) — recomputing under the wrong plan
            # would bank misindexed chunks, so fail loudly instead
            raise RuntimeError(
                f"campaign leg: leased chunk(s) {missing} never formed "
                f"under this run's emission plan "
                f"(chunks_total={camp_plan.get('chunks_total')}); the "
                "campaign key no longer matches the checkpoint dir"
            )
        t_end = time.perf_counter()
        timings["total_s"] = round(t_end - t_start, 6)
        fault_stats = faults.counters.delta(fault_snap)
        stats = {
            "n_points": int(n),
            "n_partitions": int(p_true),
            "campaign_partial": True,
            "campaign_tier": campaign.tier,
            "campaign_chunks_done": sorted(camp_done),
            "campaign_chunks_total": camp_plan.get("chunks_total"),
            "faults": fault_stats,
            "timings": timings,
        }
        obs.add_span(
            "train",
            t_start,
            t_end,
            n=int(n),
            metric=cfg.metric,
            n_partitions=int(p_true),
            campaign_chunks=len(camp_done),
        )
        obs.flush()
        return TrainOutput(
            np.empty(0, np.int32), np.empty(0, np.int8), [], 0, stats
        )

    # 5. per-partition clustering on device, one launch per bucket width
    # (ascending; same widths recur across runs -> jit cache hits).
    # Dispatch every bucket group before blocking on any result: jax
    # execution is async, so the device works through the groups while the
    # host runs every device-INDEPENDENT phase below — instance tables, band
    # membership, inner membership — and only then blocks on the labels.
    # Banded groups go out as phase 1 (counts/core/cell-edge bits); their
    # phase 2 follows after the host cell-components pass.

    # Compact-transfer path: the device link runs at ~15 MB/s down with
    # ~0.5 s/pull latency, so instead of pulling every group's [P, B]
    # core+bits (5 B/slot), dispatch a device post-pass that packs the core
    # mask 8x and scans per-cell OR masks, keeping the raw bits in HBM for
    # a targeted border-candidate gather (ops/banded.py::banded_postpass).
    # Under a mesh the phase-1 outputs arrive sharded over the partition
    # axis; the postpass is BLOCK-local (SCAN_BLOCK divides every shard's
    # P*B slots), so GSPMD partitions the pack/scan along the same axis and
    # only the small or_idx gather and the final combo pull cross shards —
    # multi-chip runs keep the ~16x pull reduction instead of falling back
    # to full [P, B] pulls (VERDICT r1 item 4).
    # The postpass concatenates its groups into flat [M]-slot device
    # arrays; a single buffer must stay under 2^31 BYTES (the TPU
    # runtime's per-buffer addressing limit — exceeding it kills the
    # worker outright, observed at ~500M slots where the int32 bits_flat
    # crosses 2 GB). The eager machinery above already chunked the
    # groups under that cap during packing; here the tail chunk flushes
    # and the pulled artifacts get merged host-side with rebased layout
    # offsets — finalize_compact is global-cell-id based and a partition
    # lives in exactly one group, so no cell edge crosses chunks and one
    # merged finalize is exact. Per-chunk int32 gather indices
    # (_pad_idx) are safe by the same cap.
    if compact_on and cellmeta is not None:
        _pull_before_tail = eager["pull_spent"]
        with _abort_guard():
            _flush_chunk()
            # defensive: a placeholder that never filled (the emission
            # plan diverged from the saved one — e.g. a changed
            # group-slot cap slipping past the fingerprint) re-chunks
            # whatever arrived via the divergence path instead of
            # deadlocking the finalize; its stale file is invalidated
            # either way
            for _rec in eager["records"]:
                if "pending_loaded" in _rec:
                    if _rec["ch"]:
                        _complete_placeholder(_rec)
                    elif ckpt_fp is not None:
                        from dbscan_tpu.parallel import (
                            checkpoint as _ckpt_p1,
                        )

                        _ckpt_p1.invalidate_p1_chunk(
                            checkpoint_dir, _rec["ci"]
                        )
            _flush_chunk()  # divergence re-chunk may have reopened `cur`
        eager["records"] = [
            r
            for r in eager["records"]
            if "pending_loaded" not in r and "dropped" not in r
        ]
        _tail_pull = eager["pull_spent"] - _pull_before_tail
    else:
        _tail_pull = 0.0
    compact = eager["records"] or None
    t0 = _mark("postdispatch_s", t0)
    timings["postdispatch_s"] = round(
        timings["postdispatch_s"] - _tail_pull, 6
    )

    def _slotmap(g):
        # valid slots are the per-row prefix 0..count-1 (binning packers'
        # layout invariant): build (rows, slots) arithmetically instead of
        # scanning the [P, B] buffer
        if g.row_counts is None:
            return np.nonzero(g.point_idx >= 0)
        nat = _native.prefix_maps(g.row_counts)
        if nat is not None:
            return nat
        c = g.row_counts
        rows = np.repeat(np.arange(len(c)), c)
        slots = np.arange(int(c.sum()), dtype=np.int64) - np.repeat(
            np.cumsum(c) - c, c
        )
        return rows, slots

    # (rows, slots) maps are only needed by the numpy-fallback branches —
    # with the native library loaded nothing ever indexes them — so build
    # them lazily per group
    _slotmap_cache: dict = {}

    def _slotmap_of(i: int):
        if i not in _slotmap_cache:
            _slotmap_cache[i] = _slotmap(pending[i][0])
        return _slotmap_cache[i]

    def _per_group_tables():
        parts_l, ptidx_l = [], []
        for i, (g, _) in enumerate(pending):
            nat = (
                _native.repeat_i64(g.part_ids, g.row_counts)
                if g.row_counts is not None
                else None
            )
            if nat is not None:
                parts_l.append(nat)
                ptidx_l.append(_native.extract_prefix(g.point_idx, g.row_counts))
            else:
                rows, slots = _slotmap_of(i)
                parts_l.append(g.part_ids[rows])
                ptidx_l.append(g.point_idx[rows, slots])
        return parts_l, ptidx_l

    if pending:
        _parts_l, _ptidx_l = _per_group_tables()
        inst_part = np.concatenate(_parts_l)
        inst_ptidx = np.concatenate(_ptidx_l)
    else:
        inst_part = np.empty(0, np.int64)
        inst_ptidx = np.empty(0, np.int64)

    # device-independent merge precomputation (overlaps the device window)
    if rp is not None:
        from dbscan_tpu.parallel.spill import band_membership

        cand_rp, inst_inner = band_membership(
            inst_part, inst_ptidx, rp[3], n
        )
        band_any = np.zeros(n, dtype=bool)
        band_any[inst_ptidx[cand_rp]] = True
    elif rects_int is not None:
        band_any, inst_inner = _classify_instances(
            grid_pts, cells, cell_inv, rects_int, margins, inst_part,
            inst_ptidx,
        )
    else:
        band_any = _band_membership(pts, margins, part_ids, point_idx)
        inst_inner = geo.almost_contains(
            margins.inner[inst_part], pts[inst_ptidx, :2]
        )
    cand = band_any[inst_ptidx]
    t0 = _mark("overlap_host_s", t0)

    # host finalize for the banded groups (blocks on their device sweeps):
    # cell-graph components, seeds, and the full border algebra — the
    # reference's driver-side graph pass (DBSCANGraph.scala:70-87)
    # transplanted to per-partition scale (parallel/cellgraph.py)
    if compact:
        tfin = time.perf_counter()
        pull_prior = eager["pull_spent"]

        def _host_finalize():
            """The host-oracle finalize (and the device path's degrade
            target): pull any chunks still on the device (the eager
            pipeline leaves the last one live), then merge every chunk
            into ONE flat space (chunk bases stack in order) so the
            per-group label algebra runs once: group-local ``starts``
            need no rebase, ``bases``/``or_starts``/border positions
            shift by the running chunk offsets. Checkpoint-loaded
            chunks re-derive their layout and border positions from the
            re-packed groups + saved combo (both deterministic)."""
            tc = time.perf_counter()
            pull0 = eager["pull_spent"]
            m_bidx: list = []
            m_groups: list = []
            m_starts: list = []
            m_bases: list = []
            m_orgid: list = []
            m_orstarts: list = []
            core_l, orv_l = [], []
            bpos_l, bbits_l = [], []
            base_off = 0
            or_off = 0
            for rec in compact:
                # the last chunk is usually still live here; its pull is
                # the final place an async device fault can surface with
                # earlier chunks' artifacts worth banking (a pipelined
                # worker fault re-raises at this wait — same guard)
                with _abort_guard():
                    _consume_pull(rec)
                layout = rec.get("layout")
                if layout is None:  # checkpoint-loaded chunk
                    layout = cellgraph.cell_layout(rec["groups"])
                total = layout["total"]
                combo_host = rec["combo_host"]
                core_ch = rec.get("core_ch")
                bpos_ch = rec.get("bpos")
                if core_ch is None or bpos_ch is None:
                    # checkpoint-loaded chunks re-derive both through
                    # the SAME helper _pull_record used live
                    core_ch, bpos_ch = cellgraph.unpack_combo(
                        combo_host, layout
                    )
                orv_l.append(
                    combo_host[total // 8 :].view("<i4")[
                        : len(layout["or_pos"])
                    ]
                )
                core_l.append(core_ch)
                bpos_l.append(bpos_ch + base_off)
                bbits_l.append(rec["bbits"])
                m_bidx.extend(rec["ch"])
                m_groups.extend(rec["groups"])
                m_starts.extend(layout["starts"])
                m_bases.extend(b + base_off for b in layout["bases"])
                m_orgid.append(layout["or_gid"])
                m_orstarts.append(layout["or_starts"] + or_off)
                base_off += total
                or_off += len(layout["or_pos"])
            core_flat = (
                np.concatenate(core_l) if len(core_l) > 1 else core_l[0]
            )
            or_vals = np.concatenate(orv_l) if len(orv_l) > 1 else orv_l[0]
            border_pos = (
                np.concatenate(bpos_l) if len(bpos_l) > 1 else bpos_l[0]
            )
            m_layout = {
                "starts": m_starts,
                "bases": m_bases,
                "total": base_off,
                "or_gid": np.concatenate(m_orgid),
                "or_starts": np.concatenate(m_orstarts),
            }
            # pulls that happened before this loop (packing-window + tail
            # flush, snapshotted as pull0 at loop start) are reported here —
            # dispatch_s/postdispatch_s excluded them — and the loop's own
            # wall already contains ITS pulls exactly once
            timings["cellcc_pull_core_s"] = round(
                time.perf_counter() - tc + pull0, 6
            )
            tc = time.perf_counter()
            border_bits = (
                np.concatenate(bbits_l) if len(bbits_l) > 1 else bbits_l[0]
            )
            tc = _mark("cellcc_pull_rest_s", tc)
            fin = cellgraph.finalize_compact(
                m_groups, m_layout, cellmeta, cfg.engine.value, core_flat,
                or_vals, border_pos, border_bits,
            )
            _mark("cellcc_host_s", tc)
            return m_bidx, fin

        def _device_finalize():
            """One fused cellcc.cc dispatch over the staged chunks +
            the [V]-label pull: the whole cell-CC/border finalize stays
            on device (cellgraph.finalize_device). Idempotent — nothing
            is mutated before the pull lands — so a supervised retry
            re-dispatches from intact inputs, and the records' combo/
            bits handles are untouched for the host degrade path."""
            tc = time.perf_counter()
            wintab_dev = _wintab_dev()
            m_bidx: list = []
            counts: list = []
            for rec in compact:
                m_bidx.extend(rec["ch"])
                for g in rec["groups"]:
                    counts.append(
                        int(g.row_counts.sum())
                        if g.row_counts is not None
                        else int((g.point_idx >= 0).sum())
                    )
            out_slots = binning._ratchet(
                getattr(cfg, "shape_floors", None),
                "cellcc_out",
                binning._ladder_width(max(1, sum(counts)), 4096),
            )
            seeds_dev, flags_dev, iters_dev = cellgraph.finalize_device(
                [rec["dev"] for rec in compact],
                wintab_dev,
                cfg.engine.value,
                out_slots,
                prop_mode=cellcc_dev["prop_mode"],
            )

            def _pull_labels():
                return (
                    mesh_mod.pull_to_host(seeds_dev),
                    mesh_mod.pull_to_host(flags_dev),
                    mesh_mod.pull_to_host(iters_dev),
                )

            if pull_pipe is not None and not eager.get("aborting"):
                # the thin label pull rides the PR-5 engine: D2H streams
                # on the worker (stall telemetry included) while the
                # host stages the split below
                job = pull_pipe.submit(
                    _pull_labels,
                    on_start=getattr(
                        seeds_dev, "copy_to_host_async", None
                    ),
                    bytes_hint=5 * out_slots,
                    label="cellcc_labels",
                )
                seeds_h, flags_h, iters_h = pull_pipe.settle(
                    job, _pull_labels
                )
            else:
                seeds_h, flags_h, iters_h = _pull_labels()
            timings["cellcc_pull_core_s"] = round(
                time.perf_counter() - tc + pull_prior, 6
            )
            tc = time.perf_counter()
            iters = int(np.asarray(iters_h))
            cellcc_dev["iters"] = iters
            obs.count("cellcc.cc_iters", iters)
            # the shared propagation telemetry: every settled window_cc
            # consumer funnels its sweep count here (leg-1's win is
            # measured everywhere the fixed point runs, not just cellcc)
            from dbscan_tpu.ops import propagation as prop_mod

            prop_mod.note_sweeps(iters, cellcc_dev["prop_mode"])
            fin = cellgraph.split_device_labels(seeds_h, flags_h, counts)
            timings["cellcc_host_s"] = round(time.perf_counter() - tc, 6)
            return m_bidx, fin

        def _drop_staged():
            # free the staged per-cell partials/metadata (~13 B/slot):
            # on the degrade path BEFORE the host oracle dispatches —
            # they are the very allocations a RESOURCE_EXHAUSTED fault
            # implicates (the mid-run residency degrade already does
            # this) — and on success before the merge phases run
            for r in compact:
                r.pop("dev", None)

        def _host_fallback():
            _drop_staged()
            return _host_finalize()

        if cellcc_dev["on"] and all("dev" in r for r in compact):
            # supervised like any dispatch: transient faults retry the
            # fused CC, exhaustion degrades the WHOLE finalize to the
            # host oracle with labels intact (the records still hold
            # their combo/bits device handles)
            with _abort_guard():
                m_bidx, finalized = faults.supervised(
                    faults.SITE_CELLCC,
                    lambda _b: _device_finalize(),
                    fallback=_host_fallback,
                    label="device cellcc finalize",
                )
            _drop_staged()
        else:
            m_bidx, finalized = _host_finalize()
        for i, (seeds_np, flags_np) in zip(m_bidx, finalized):
            g = pending[i][0]
            pending[i] = (
                g, (seeds_np, flags_np, int((flags_np == CORE).sum()))
            )
        # whole-finalize wall, both modes: this block's window plus the
        # chunk pulls charged to pull_spent before it (they were part
        # of the finalize work, just overlapped with dispatch)
        timings["cellcc_finalize_s"] = round(
            time.perf_counter() - tfin + pull_prior, 6
        )
        obs.add_span(
            "cellcc.finalize",
            tfin,
            time.perf_counter(),
            mode="device" if cellcc_dev["iters"] else "host",
            cc_iters=int(cellcc_dev["iters"]),
            pull_prior_s=round(pull_prior, 6),
        )
    elif cellmeta is not None:
        b_idx = [i for i, (g, _) in enumerate(pending) if g.banded is not None]
        if b_idx:  # DBSCAN_NO_COMPACT=1 debug runs only: full [P, B]
            # pulls (every size goes through the chunked compact path
            # otherwise)
            p1_np = [
                (
                    pending[i][0],
                    mesh_mod.pull_to_host(pending[i][1][0]),
                    mesh_mod.pull_to_host(pending[i][1][1]),
                )
                for i in b_idx
            ]
            finalized = cellgraph.finalize_from_bits(
                p1_np, cellmeta, cfg.engine.value
            )
            for i, (seeds_np, flags_np) in zip(b_idx, finalized):
                g = pending[i][0]
                pending[i] = (
                    g,
                    (seeds_np, flags_np, int((flags_np == CORE).sum())),
                )
    t0 = _mark("cellcc_s", t0)

    n_core = 0
    inst_seed_l, inst_flag_l = [], []

    def _group_rows(i, g, seeds_dev, flags_dev):
        """Pull one group's seed/flag buffers and extract the valid
        prefix rows. On the pull worker (pipelined) group k+1's
        transfer/device-wait overlaps group k's host extraction; the
        serial path runs it inline, exactly the pre-pipeline loop."""
        seeds_g = mesh_mod.pull_to_host(seeds_dev)
        flags_g = mesh_mod.pull_to_host(flags_dev)
        if seeds_g.ndim == 1:
            # finalize_compact already emits flat valid-prefix arrays in
            # instance order
            return seeds_g, flags_g
        es = (
            _native.extract_prefix(seeds_g, g.row_counts)
            if g.row_counts is not None
            else None
        )
        if es is not None:
            return es, _native.extract_prefix(flags_g, g.row_counts)
        rows, slots = _slotmap_of(i)
        return seeds_g[rows, slots], flags_g[rows, slots]

    group_jobs = None
    if pull_pipe is not None and pending:
        group_jobs = [
            pull_pipe.submit(
                functools.partial(_group_rows, i, g, sd, fd),
                bytes_hint=int(getattr(sd, "nbytes", 0))
                + int(getattr(fd, "nbytes", 0)),
                label=f"group{i}",
            )
            for i, (g, (sd, fd, _nc)) in enumerate(pending)
        ]
    for i, (g, (seeds_dev, flags_dev, nc)) in enumerate(pending):
        n_core += int(nc)
        if group_jobs is not None:
            # settle = wait + brake-on-fault + serial fallback for a
            # job a concurrent abort cancelled (buffers untouched)
            es, ef = pull_pipe.settle(
                group_jobs[i],
                functools.partial(_group_rows, i, g, seeds_dev, flags_dev),
            )
        else:
            es, ef = _group_rows(i, g, seeds_dev, flags_dev)
        inst_seed_l.append(es)
        inst_flag_l.append(ef)
    inst_seed = np.concatenate(inst_seed_l) if inst_seed_l else np.empty(0, np.int32)
    inst_flag = np.concatenate(inst_flag_l) if inst_flag_l else np.empty(0, np.int8)
    t0 = _mark("device_s", t0)

    # sweep work the device actually ran (_group_flops per dispatched
    # group, checkpoint-covered skips excluded); divided by the isolated
    # window (timings["banded_p1_sync_s"] under DBSCAN_TIME_DEVICE=1)
    # this grounds the bench's MFU figure
    banded_sweep_flops = flops_spent[0]
    banded_sweep_bytes = bytes_spent[0]

    # supervised-dispatch accounting for THIS run (delta over the
    # process-global counters): attempts/retries/fallbacks plus the
    # total backoff wall. THREE views exist and stats["faults"] is the
    # AUTHORITATIVE per-run figure: timings["fault_backoff_s"] mirrors
    # its backoff_s (backoff is wall the run really spent sleeping, so
    # it belongs in the phase table), and the obs `faults.*` counters
    # are the PROCESS-CUMULATIVE stream the trace events ride — their
    # per-run delta equals stats["faults"] field-for-field (pinned by
    # tests/test_obs.py). The trace additionally carries this run's
    # delta as a `faults.run_delta` instant so a trace file alone can
    # be cross-checked against the captured stats.
    fault_stats = faults.counters.delta(fault_snap)
    timings["fault_backoff_s"] = fault_stats["backoff_s"]
    obs.event("faults.run_delta", **fault_stats)

    # core stats: one schema shared by the final output, the checkpoint
    # scalars, and (verbatim) the resumed run's stats
    core_stats = {
        "n_points": n,
        "n_partitions": int(p_true),
        "bucket_size": int(max_b),
        "n_bucket_groups": len(groups),
        "n_banded_groups": sum(1 for g in groups if g.banded is not None),
        "banded_sweep_flops": int(banded_sweep_flops),
        "banded_sweep_bytes": int(banded_sweep_bytes),
        "effective_maxpp": int(maxpp_eff),
        "duplication_factor": float(len(part_ids)) / max(1, n),
        "n_core_instances": int(n_core),
        "projected": sph is not None,  # spherical embedding in effect
        "spill_tree": rp is not None,  # metric spill partitioning in effect
        # level-synchronous device-tree rounds (0: host recursion or no
        # spill) — bench stamps this next to spill_partition_s
        "spill_levels": int(spill_info.get("levels", 0)),
        # device cellcc-finalize CC sweeps (0: host-oracle finalize ran,
        # whether by knob, structural exclusion, or fault degrade) —
        # bench stamps this next to cellcc_finalize_s so the history
        # gate catches propagation-count blowups, not just walls
        "cellcc_cc_iters": int(cellcc_dev["iters"]),
        # shared-propagation figures (ops/propagation.py): the run's
        # window_cc sweep count (the banded path's device CC sweeps —
        # 0 when the host oracle ran) and the resolved mode, so bench
        # rows stamp {prefix}_prop_sweeps next to _cellcc_cc_iters and
        # the history gate trends leg-1's sweep collapse directly
        "prop_sweeps": int(cellcc_dev["iters"]),
        "prop_mode": _resolved_prop_mode(cellcc_dev),
        "faults": fault_stats,
    }

    if ckpt_fp is not None:
        from dbscan_tpu.parallel import checkpoint as _ckpt

        _ckpt.save_premerge(
            checkpoint_dir,
            ckpt_fp,
            arrays={
                "inst_part": inst_part,
                "inst_ptidx": inst_ptidx,
                "inst_seed": inst_seed,
                "inst_flag": inst_flag,
                "cand": cand,
                "inst_inner": inst_inner,
                "rects": (
                    margins.main
                    if margins is not None
                    else np.empty((0, 4), np.float64)
                ),
            },
            scalars=core_stats,
        )
        timings["checkpoint_s"] = round(time.perf_counter() - t0, 6)
        t0 = time.perf_counter()

    # 6-9. local ids, cross-partition merge, relabel + dedup — shared with
    # the sparse spill front-end (ops/sparse.py), which produces its own
    # instance tables.
    res_cluster, res_flag, n_clusters = finalize_merge(
        inst_part, inst_ptidx, inst_seed, inst_flag, cand, inst_inner,
        n, p_true, max_b, canonical=rp is not None,
        mesh=mesh, shape_floors=getattr(cfg, "shape_floors", None),
    )

    # spill-tree partitions have no rectangle representation
    partitions = (
        [] if margins is None
        else [(i, margins.main[i]) for i in range(p_true)]
    )
    t_end = time.perf_counter()
    timings["merge_s"] = round(t_end - t0, 6)
    timings["total_s"] = round(t_end - t_start, 6)
    stats = {**core_stats, "n_clusters": n_clusters, "timings": timings}
    if pull_pipe is not None:
        # this run's pull-pipeline accounting (engine totals are
        # process-cumulative; the delta is the per-run figure, the same
        # snapshot/delta discipline as stats["faults"]). overlap_ratio
        # is what bench stamps as pull_overlap_ratio.
        stats["pull"] = pipe_mod.delta_totals(pull_snap, pull_pipe.totals())
    obs.add_span(
        "train",
        t_start,
        t_end,
        n=int(n),
        metric=cfg.metric,
        n_partitions=int(p_true),
        n_clusters=int(n_clusters),
    )
    obs.flush()  # rewrite DBSCAN_TRACE's file (atomic; cumulative)
    return TrainOutput(res_cluster, res_flag, partitions, n_clusters, stats)
