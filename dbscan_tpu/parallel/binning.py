"""Host-side halo binning: margins, eps-halo duplication, static bucketing.

This is the TPU replacement for the reference's broadcast + shuffle stages
(DBSCAN.scala:116-152): instead of shipping margin lists to executors and
shuffling points through groupByKey, the host computes margins, replicates
each point into every partition whose grown rectangle contains it, and packs
the result into STATIC [P, B, ...] device buffers (padding + mask) so one
compiled kernel handles every partition — no dynamic shapes under jit.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Tuple

import numpy as np

from dbscan_tpu.ops import geometry as geo


class Margins(NamedTuple):
    """Per-partition (inner, main, outer) float rects, the reference's
    Margins triple (DBSCAN.scala:70, :116-121): inner = main shrunk by eps,
    outer = main grown by eps."""

    inner: np.ndarray  # [P, 4]
    main: np.ndarray  # [P, 4]
    outer: np.ndarray  # [P, 4]


def build_margins(rects_int: np.ndarray, cell_size: float, eps: float) -> Margins:
    """Margins from integer partition rects (DBSCAN.scala:116-121)."""
    main = geo.int_rects_to_float(np.asarray(rects_int).reshape(-1, 4), cell_size)
    return Margins(
        inner=geo.shrink(main, eps), main=main, outer=geo.shrink(main, -eps)
    )


def duplicate_points(
    points: np.ndarray, outer: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """eps-halo replication: every (partition, point) pair with
    outer.contains(point) (DBSCAN.scala:132-137), vectorized and chunked over
    points. Returns (part_ids [M], point_idx [M]) sorted by partition then
    point order."""
    pts = np.asarray(points, dtype=np.float64)[:, :2]
    P = outer.shape[0]
    part_ids = []
    point_idx = []
    # bound the [P, chunk] bool intermediate regardless of partition count
    chunk = max(1, int(2**24 // max(1, P)))
    for s in range(0, len(pts), chunk):
        c = pts[s : s + chunk]
        inside = geo.contains_point(outer[:, None, :], c[None, :, :])  # [P, nc]
        p, i = np.nonzero(inside)
        part_ids.append(p)
        point_idx.append(i + s)
    part_ids = np.concatenate(part_ids) if part_ids else np.empty(0, np.int64)
    point_idx = np.concatenate(point_idx) if point_idx else np.empty(0, np.int64)
    order = np.lexsort((point_idx, part_ids))
    return part_ids[order].astype(np.int64), point_idx[order]


def duplicate_points_grid(
    points: np.ndarray,
    cells: np.ndarray,
    inverse: np.ndarray,
    rects_int: np.ndarray,
    outer: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray]:
    """Grid-pruned eps-halo replication — same output as
    :func:`duplicate_points`, O(N + boundary) instead of O(P * N).

    A point can lie in partition p's outer rect (main grown by eps) only if
    p owns a cell in the 3x3 ring around the point's own 2eps cell: the
    eps-disk around any point of cell c stays inside c grown by eps, which
    the ring covers with an eps margin to spare. So candidates come from a
    cell -> owner lookup (9 per UNIQUE cell, not per point); the own cell's
    owner always contains the point (cell c main_p c outer_p, with eps
    margin dwarfing the snap function's worst-case ulp misassignment), and
    only ring candidates with a different owner take the exact
    outer-containment test — a boundary-band minority.

    Args:
      points: [N, >=2] float64.
      cells: [C, 2] int64 unique occupied cell indices (cell_histogram_int).
      inverse: [N] int64 row into `cells` per point.
      rects_int: [P, 4] integer partition rects in cell units (half-open:
        covering cells x..x2-1, y..y2-1).
      outer: [P, 4] float grown rects (binning.Margins.outer).

    Returns (part_ids [M], point_idx [M]) sorted by partition then point
    order — bit-identical to duplicate_points.
    """
    pts = np.asarray(points, dtype=np.float64)[:, :2]
    n = len(pts)
    rects_int = np.asarray(rects_int, dtype=np.int64).reshape(-1, 4)
    p_n = rects_int.shape[0]
    if p_n <= 1 or n == 0:
        return duplicate_points(pts, outer)
    grid_cells = (int(rects_int[:, 2].max()) - int(rects_int[:, 0].min())) * (
        int(rects_int[:, 3].max()) - int(rects_int[:, 1].min())
    )
    if grid_cells > 2**27:  # dense owner grid > 0.5 GB: sparse/huge-extent
        return duplicate_points(pts, outer)  # data; bounded-memory fallback

    gx0 = int(rects_int[:, 0].min())
    gy0 = int(rects_int[:, 1].min())
    gw = int(rects_int[:, 2].max()) - gx0
    gh = int(rects_int[:, 3].max()) - gy0
    owner = np.full((gw, gh), -1, dtype=np.int32)
    for p in range(p_n):
        x, y, x2, y2 = rects_int[p] - (gx0, gy0, gx0, gy0)
        owner[x:x2, y:y2] = p

    # Ring owners per UNIQUE cell. Neighbors are clamped to the grid: a
    # clamped lookup can only repeat an in-grid owner (dedup absorbs it);
    # out-of-grid cells are unowned so nothing is missed.
    cx = np.clip(cells[:, 0] - gx0, 0, gw - 1)
    cy = np.clip(cells[:, 1] - gy0, 0, gh - 1)
    own = owner[cx, cy]  # [C]; every occupied cell is owned
    ring = np.empty((len(cells), 8), dtype=np.int32)
    k = 0
    for dx in (-1, 0, 1):
        for dy in (-1, 0, 1):
            if dx == 0 and dy == 0:
                continue
            ring[:, k] = owner[
                np.clip(cx + dx, 0, gw - 1), np.clip(cy + dy, 0, gh - 1)
            ]
            k += 1
    # distinct foreign candidates per cell: sort the 8, drop repeats/own/-1
    ring.sort(axis=1)
    cand = (
        (ring >= 0)
        & (ring != own[:, None])
        & np.c_[np.ones(len(cells), bool), ring[:, 1:] != ring[:, :-1]]
    )
    ccell, ck = np.nonzero(cand)  # candidate (cell row, ring slot) pairs

    # Expand candidate (cell, partition) pairs to their points and run the
    # exact inclusive containment test (only boundary-band cells get here).
    part_base = own[inverse]  # [N] own-cell owner, in point order
    if ccell.size:
        order_pts = np.argsort(inverse.astype(np.int32), kind="stable")
        cstart = np.searchsorted(inverse[order_pts], np.arange(len(cells) + 1))
        ccount = cstart[ccell + 1] - cstart[ccell]
        cpart = ring[ccell, ck]
        pt = order_pts[
            np.repeat(cstart[ccell], ccount)
            + (
                np.arange(ccount.sum(), dtype=np.int64)
                - np.repeat(np.cumsum(ccount) - ccount, ccount)
            )
        ]
        pp = np.repeat(cpart, ccount)
        hit = geo.contains_point(outer[pp], pts[pt])
        halo_part, halo_pt = pp[hit], pt[hit]
    else:
        halo_part = np.empty(0, np.int32)
        halo_pt = np.empty(0, np.int64)

    part_ids = np.concatenate([part_base.astype(np.int64), halo_part])
    point_idx = np.concatenate([np.arange(n, dtype=np.int64), halo_pt])
    okey = part_ids * n + point_idx
    order = np.argsort(
        okey.astype(np.int32) if p_n * n < 2**31 else okey, kind="stable"
    )
    return part_ids[order], point_idx[order]


def _ladder_width(c: int, bucket_multiple: int) -> int:
    """Round a count up along a ~1.5x geometric ladder of bucket_multiple
    multiples (q in 1, 1.5, 2, 3, 4, 6, ... when it divides evenly): area
    waste bounded at ~2.25x worst-case vs exact, while widths recur across
    runs so the compile cache stays small."""
    c = max(1, int(c))
    q_needed = math.ceil(c / bucket_multiple)
    q = 1
    while q < q_needed:
        nq = q * 3 // 2 if (q & (q - 1)) == 0 else q * 4 // 3
        q = nq if nq > q else q + 1  # progress even at q=1
    return q * bucket_multiple


class BandedExtras(NamedTuple):
    """Cell-sorted block-slab metadata for the banded engine
    (dbscan_tpu/ops/banded.py). All arrays are indexed by SORTED position;
    B is a multiple of ops.banded.BANDED_BLOCK.

    fold_idx: [P_pad, B] int32 original fold index per position (identity on
    padding); pos_of_fold: [P_pad, B] int32 inverse permutation;
    rel_starts/spans: [P_pad, B, 3] int32 per-point candidate runs (one per
    neighboring cell row), starts relative to the row's block slab;
    slab_starts: [P_pad, B // BANDED_BLOCK, 3] int32 absolute slab origins;
    slab: static S >= every slab length (slab_start + S <= B).
    """

    fold_idx: np.ndarray
    pos_of_fold: np.ndarray
    rel_starts: np.ndarray
    spans: np.ndarray
    slab_starts: np.ndarray
    slab: int


class BucketGroup(NamedTuple):
    """One same-width slab of partitions (see :func:`bucketize_grouped`).

    points: [P_pad, B, D]; mask: [P_pad, B] validity; point_idx: [P_pad, B]
    original row index (-1 padding); part_ids: [P_pad] ORIGINAL partition id
    per row, -1 on padding partitions. banded: window metadata when this
    group runs the banded engine (points then sit in cell-sorted order),
    None for the dense engine (fold order).
    """

    points: np.ndarray
    mask: np.ndarray
    point_idx: np.ndarray
    part_ids: np.ndarray
    banded: BandedExtras = None


def bucketize_grouped(
    points: np.ndarray,
    part_ids: np.ndarray,
    point_idx: np.ndarray,
    n_parts: int,
    bucket_multiple: int = 128,
    pad_parts_to: int = 1,
    dtype=np.float32,
) -> Tuple[list, int]:
    """Pack partitions into SIZE-GROUPED static buffers.

    One global bucket width would make every partition pay the largest
    partition's O(B^2) sweep cost; here each partition's width is its count
    rounded up along a ~1.5x geometric ladder of ``bucket_multiple``
    multiples (1, 2, 3, 4, 6, 8, 12, ... x) — widths recur across runs so
    the compile cache stays bounded, with per-partition padding waste under
    2x (1.5x asymptotically; the ladder's first rung is 1 -> 2) — and
    partitions of equal width share one [P_g, B_g] slab. Total device work drops from P * B_max^2 toward
    sum(B_i^2). The group's partition axis pads to `pad_parts_to` (device
    count) with empty partitions, like bucketize.

    Returns (groups sorted by ascending width, max width).
    """
    pts = np.asarray(points)
    d = pts.shape[1]
    counts = np.bincount(part_ids, minlength=n_parts)

    widths = np.array(
        [_ladder_width(c, bucket_multiple) for c in counts], dtype=np.int64
    )
    starts = np.searchsorted(part_ids, np.arange(n_parts))
    slot_all = (
        np.arange(part_ids.size) - np.repeat(starts, counts)
        if part_ids.size
        else np.empty(0, np.int64)
    )

    groups = []
    max_b = 0
    for b in sorted(set(widths.tolist())):
        sel_parts = np.flatnonzero(widths == b)
        p_pad = max(1, math.ceil(len(sel_parts) / pad_parts_to) * pad_parts_to)
        buf = np.zeros((p_pad, b, d), dtype=dtype)
        mask = np.zeros((p_pad, b), dtype=bool)
        idx = np.full((p_pad, b), -1, dtype=np.int64)
        pid = np.full(p_pad, -1, dtype=np.int64)
        pid[: len(sel_parts)] = sel_parts
        if part_ids.size:
            row_of_part = np.full(n_parts, -1, dtype=np.int64)
            row_of_part[sel_parts] = np.arange(len(sel_parts))
            in_group = row_of_part[part_ids] >= 0
            gi = np.flatnonzero(in_group)
            rows = row_of_part[part_ids[gi]]
            slots = slot_all[gi]
            buf[rows, slots] = pts[point_idx[gi]].astype(dtype)
            mask[rows, slots] = True
            idx[rows, slots] = point_idx[gi]
        groups.append(BucketGroup(buf, mask, idx, pid))
        max_b = max(max_b, b)
    return groups, max_b


# Cell size safety factor over eps: a pair the device's f32 distance test
# could accept (true distance <= eps * (1 + few ulps)) must lie within the
# 3x3 cell ring, so cells are built marginally larger than eps. 1e-5 covers
# f32's ~1e-7/op rounding with orders of magnitude to spare, while growing
# windows imperceptibly.
CELL_SLACK = 1.0 + 1e-5

# Partitions narrower than this always use the dense engine: at small B the
# [B, B] sweep is already cheap and window bookkeeping is pure overhead.
MIN_BANDED_BUCKET = 4096

# At or above this width the dense engine is no longer an option at all — a
# [B, B] f32 measure matrix at B = 65536 is 17 GB, past a v5e chip's HBM —
# so auto ALWAYS routes such partitions through the banded engine. Below
# it, measured crossover on v5e: the dense sweep's perfectly-tiled [B, B]
# broadcasts beat the banded slab machinery unless the slabs shrink the
# work by a margin larger than their per-block overheads (~an order of
# magnitude).
DENSE_MAX_BUCKET = 65536

# Rows per block-slab tile in the banded engine; banded bucket widths are
# padded to a multiple of this. Bigger blocks amortize the per-slab DMA
# latency over more rows but widen the union slab S (waste ~6 cells'
# occupancy); 1024 measured fastest on v5e at bench densities. Lives here
# (host side) so the packer has no jax dependency; dbscan_tpu/ops/banded.py
# imports it.
BANDED_BLOCK = 1024


def bucketize_banded(
    points: np.ndarray,
    part_ids: np.ndarray,
    point_idx: np.ndarray,
    n_parts: int,
    eps: float,
    outer: np.ndarray,
    bucket_multiple: int = 128,
    pad_parts_to: int = 1,
    dtype=np.float32,
    force: bool = False,
) -> Tuple[list, int]:
    """Pack partitions for the banded engine (dbscan_tpu/ops/banded.py).

    Per partition: snap instances to an eps-sized grid anchored at the
    partition's outer rect, sort by cell row-major (stable, so equal-cell
    points keep fold order), and precompute each point's three contiguous
    candidate runs — one per neighboring cell row — in the sorted order.
    Runs are then grouped by blocks of BANDED_BLOCK consecutive rows: the
    per-(block, cell row) union of runs is the contiguous SLAB the device
    fetches with one dynamic_slice; the static slab bound S is the padded
    max slab length. Partitions where 3*S gives no real saving over the
    dense [B, B] sweep (or below MIN_BANDED_BUCKET, unless ``force``) fall
    back to dense groups.

    Groups by (width, S) for banded parts and width for dense parts; returns
    (groups, max width) like :func:`bucketize_grouped`, with ``banded`` set
    on the banded groups.
    """
    pts = np.asarray(points)
    if pts.shape[1] != 2:
        raise ValueError(f"banded bucketing is 2-D only, got D={pts.shape[1]}")
    m_tot = part_ids.size
    counts = np.bincount(part_ids, minlength=n_parts)
    part_start = np.concatenate([[0], np.cumsum(counts)])[:-1]
    widths_b = np.array(
        [_ladder_width(c, bucket_multiple) for c in counts], dtype=np.int64
    )

    if m_tot == 0:
        return bucketize_grouped(
            points, part_ids, point_idx, n_parts, bucket_multiple,
            pad_parts_to, dtype,
        )

    cell = float(eps) * CELL_SLACK
    xy = np.asarray(pts, dtype=np.float64)[point_idx]
    # Cells must be computed from the coordinates the DEVICE sees: under
    # f32/bf16 the cast can move a point across a float64 cell boundary
    # (quantization error scales with |coordinate|, far beyond CELL_SLACK's
    # arithmetic-rounding margin), and a run built from the float64 cell
    # would miss pairs the device's distance test accepts.
    xy_dev = xy.astype(dtype).astype(np.float64)
    inv_cell = 1.0 / cell
    ox = outer[part_ids, 0]
    oy = outer[part_ids, 1]
    cx = np.maximum(np.floor((xy_dev[:, 0] - ox) * inv_cell), 0.0).astype(np.int64)
    cy = np.maximum(np.floor((xy_dev[:, 1] - oy) * inv_cell), 0.0).astype(np.int64)

    # Segment maxima via reduceat (instances are sorted by partition);
    # ufunc.at is a scalar Python-level loop — ~10s at 5M instances.
    nz = counts > 0
    segs = part_start[nz]
    cxmax = np.zeros(n_parts, dtype=np.int64)
    cymax = np.zeros(n_parts, dtype=np.int64)
    if segs.size:
        cxmax[nz] = np.maximum.reduceat(cx, segs)
        cymax[nz] = np.maximum.reduceat(cy, segs)
    stride = cxmax + 3  # cx + 2 < stride: row windows never wrap
    big = int((stride * (cymax + 2)).max()) + 1  # per-partition key space
    gkey = part_ids * big + cy * stride[part_ids] + cx

    # Stable sort by (partition, cell key): instances arrive in (partition,
    # fold) order, so ties keep fold order inside each cell. Stable argsort
    # on one packed integer key radix-sorts in O(M) — measured 4x faster
    # than np.lexsort on two keys; int32 keys shave another ~30%.
    if n_parts * big < np.iinfo(np.int32).max:
        gkey = gkey.astype(np.int32)
    order = np.argsort(gkey, kind="stable")
    p_s = part_ids[order]
    gkey_s = gkey[order]
    fold_s = (order - part_start[p_s]).astype(np.int64)
    ptidx_s = point_idx[order]
    xy_s = xy[order]
    slots_s = np.arange(m_tot, dtype=np.int64) - part_start[p_s]

    # Run boundaries per UNIQUE cell, not per instance: every instance in a
    # cell shares the same three candidate runs, and the unique-cell count U
    # is orders of magnitude below M — 6 searchsorted passes over U instead
    # of M (measured ~60x cheaper at 10M points), then one U->M gather.
    newcell = (
        np.r_[True, gkey_s[1:] != gkey_s[:-1]]
        if m_tot
        else np.empty(0, dtype=bool)
    )
    cell_first = np.flatnonzero(newcell)  # [U] first sorted pos of each cell
    ukey = gkey_s[cell_first].astype(np.int64)  # [U]
    cell_rank = np.cumsum(newcell) - 1  # [M] -> index into cell_first/ukey
    upart = p_s[cell_first]
    ustride = stride[upart]
    useg_start = part_start[upart]
    useg_end = useg_start + counts[upart]
    cell_pos = np.r_[cell_first, m_tot]  # [U+1] cell -> first sorted pos

    ustarts3 = np.empty((len(ukey), 3), dtype=np.int64)
    uspans3 = np.empty((len(ukey), 3), dtype=np.int64)
    # cell key of the run start for row (cy + dr): ukey + dr*stride - 1;
    # searchsorted over unique keys, mapped back to sorted positions via
    # cell_pos. Row validity (0 <= cy+dr <= cymax) is equivalent to the
    # segment clamp: out-of-grid rows produce empty runs inside [seg_start,
    # seg_end) because no cell carries their key — except row overflow past
    # the partition's key space, which the segment clamp catches.
    for k, dr in enumerate((-1, 0, 1)):
        lo = ukey + dr * ustride - 1
        s = cell_pos[np.searchsorted(ukey, lo)]
        e = cell_pos[np.searchsorted(ukey, lo + 3)]
        s = np.clip(s, useg_start, useg_end)
        e = np.clip(e, s, useg_end)
        ustarts3[:, k] = s - useg_start
        uspans3[:, k] = e - s
    starts3 = ustarts3[cell_rank] if m_tot else np.empty((0, 3), np.int64)
    spans3 = uspans3[cell_rank] if m_tot else np.empty((0, 3), np.int64)

    # Banded bucket widths: the dense ladder width padded up to a multiple
    # of the block size.
    t = BANDED_BLOCK
    widths_band = (widths_b + t - 1) // t * t
    nb_of = widths_band // t  # blocks per partition
    maxnb = int(nb_of.max())

    # Per-(partition block, cell row) slab = union of the block rows' runs:
    # min start / max end over valid runs.
    blk_s = slots_s // t
    bkey = p_s * maxnb + blk_s  # nondecreasing: p_s sorted, slots ascending
    n_bkeys = n_parts * maxnb
    bmin = np.zeros((n_bkeys, 3), dtype=np.int64)
    bmax = np.zeros((n_bkeys, 3), dtype=np.int64)
    run_valid = spans3 > 0
    for k in range(3):
        v = run_valid[:, k]
        bk = bkey[v]
        if bk.size == 0:
            continue
        st = starts3[v, k]
        first = np.flatnonzero(np.r_[True, bk[1:] != bk[:-1]])
        u = bk[first]
        bmin[u, k] = np.minimum.reduceat(st, first)
        bmax[u, k] = np.maximum.reduceat(st + spans3[v, k], first)

    slab_need = (bmax - bmin).max(axis=1).reshape(n_parts, maxnb).max(axis=1)
    win = np.minimum(
        np.array([_ladder_width(s, 128) for s in slab_need], dtype=np.int64),
        widths_band,  # slab can never exceed the bucket; ladder may overshoot
    )

    # Clamp slab origins so slab_start + S <= B; runs still fit (a clamped
    # origin only moves left, and run ends are bounded by the bucket width).
    part_of_bkey = np.repeat(np.arange(n_parts), maxnb)
    sstart = np.clip(bmin, 0, (widths_band - win)[part_of_bkey][:, None])

    if force:
        use_banded = counts > 0
    else:
        use_banded = (
            (counts > 0)
            & (widths_band >= MIN_BANDED_BUCKET)
            & (
                (widths_band >= DENSE_MAX_BUCKET)  # dense cannot fit HBM
                | (3 * win <= widths_band // 16)  # >=16x less sweep work
            )
        )

    groups: list = []
    max_b = 0

    # Dense fallback partitions go through the plain packer. Instances of
    # banded partitions are filtered out but n_parts keeps original ids;
    # the resulting zero-count rows land in the smallest-width group with
    # all-False masks and are skipped by the driver's instance scan.
    if not use_banded.all():
        dense_inst = ~use_banded[part_ids]
        if dense_inst.any() or not use_banded.any():
            dgroups, dmax = bucketize_grouped(
                points,
                part_ids[dense_inst],
                point_idx[dense_inst],
                n_parts,
                bucket_multiple,
                pad_parts_to,
                dtype,
            )
            groups.extend(dgroups)
            max_b = max(max_b, dmax)

    banded_inst = use_banded[p_s]
    # Per-instance run start within its slab; invalid runs (span 0) pin to 0
    # rather than inheriting a meaningless negative offset.
    rel3 = np.where(run_valid, starts3 - sstart[bkey], 0)
    for b, w in sorted(
        set(zip(widths_band[use_banded].tolist(), win[use_banded].tolist()))
    ):
        sel_parts = np.flatnonzero(
            use_banded & (widths_band == b) & (win == w)
        )
        nb = b // t
        p_pad = max(1, math.ceil(len(sel_parts) / pad_parts_to) * pad_parts_to)
        buf = np.zeros((p_pad, b, 2), dtype=dtype)
        mask = np.zeros((p_pad, b), dtype=bool)
        idx = np.full((p_pad, b), -1, dtype=np.int64)
        pid = np.full(p_pad, -1, dtype=np.int64)
        pid[: len(sel_parts)] = sel_parts
        iota = np.arange(b, dtype=np.int32)
        fold_b = np.broadcast_to(iota, (p_pad, b)).copy()
        pos_b = np.broadcast_to(iota, (p_pad, b)).copy()
        st_b = np.zeros((p_pad, b, 3), dtype=np.int32)
        sp_b = np.zeros((p_pad, b, 3), dtype=np.int32)
        sl_b = np.zeros((p_pad, nb, 3), dtype=np.int32)

        row_of_part = np.full(n_parts, -1, dtype=np.int64)
        row_of_part[sel_parts] = np.arange(len(sel_parts))
        gi = np.flatnonzero(banded_inst & (row_of_part[p_s] >= 0))
        rows = row_of_part[p_s[gi]]
        slots = slots_s[gi]
        buf[rows, slots] = xy_s[gi].astype(dtype)
        mask[rows, slots] = True
        idx[rows, slots] = ptidx_s[gi]
        fold_b[rows, slots] = fold_s[gi]
        pos_b[rows, fold_s[gi]] = slots
        st_b[rows, slots] = rel3[gi]
        sp_b[rows, slots] = spans3[gi]
        sl_b[: len(sel_parts)] = sstart[
            sel_parts[:, None] * maxnb + np.arange(nb)[None, :]
        ]

        groups.append(
            BucketGroup(
                buf, mask, idx, pid,
                BandedExtras(fold_b, pos_b, st_b, sp_b, sl_b, int(w)),
            )
        )
        max_b = max(max_b, b)
    return groups, max_b
