"""Host-side halo binning: margins, eps-halo duplication, static bucketing.

This is the TPU replacement for the reference's broadcast + shuffle stages
(DBSCAN.scala:116-152): instead of shipping margin lists to executors and
shuffling points through groupByKey, the host computes margins, replicates
each point into every partition whose grown rectangle contains it, and packs
the result into STATIC [P, B, ...] device buffers (padding + mask) so one
compiled kernel handles every partition — no dynamic shapes under jit.
"""

from __future__ import annotations

import math
import os
from typing import NamedTuple, Tuple

import numpy as np

from dbscan_tpu import _native, config, obs
from dbscan_tpu.ops import geometry as geo


class Margins(NamedTuple):
    """Per-partition (inner, main, outer) float rects, the reference's
    Margins triple (DBSCAN.scala:70, :116-121): inner = main shrunk by eps,
    outer = main grown by eps."""

    inner: np.ndarray  # [P, 4]
    main: np.ndarray  # [P, 4]
    outer: np.ndarray  # [P, 4]


def build_margins(rects_int: np.ndarray, cell_size: float, eps: float) -> Margins:
    """Margins from integer partition rects (DBSCAN.scala:116-121)."""
    main = geo.int_rects_to_float(np.asarray(rects_int).reshape(-1, 4), cell_size)
    return Margins(
        inner=geo.shrink(main, eps), main=main, outer=geo.shrink(main, -eps)
    )


def duplicate_points(
    points: np.ndarray, outer: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """eps-halo replication: every (partition, point) pair with
    outer.contains(point) (DBSCAN.scala:132-137), vectorized and chunked over
    points. Returns (part_ids [M], point_idx [M]) sorted by partition then
    point order."""
    pts = np.asarray(points, dtype=np.float64)[:, :2]
    P = outer.shape[0]
    part_ids = []
    point_idx = []
    # bound the [P, chunk] bool intermediate regardless of partition count
    chunk = max(1, int(2**24 // max(1, P)))
    for s in range(0, len(pts), chunk):
        c = pts[s : s + chunk]
        inside = geo.contains_point(outer[:, None, :], c[None, :, :])  # [P, nc]
        p, i = np.nonzero(inside)
        part_ids.append(p)
        point_idx.append(i + s)
    part_ids = np.concatenate(part_ids) if part_ids else np.empty(0, np.int64)
    point_idx = np.concatenate(point_idx) if point_idx else np.empty(0, np.int64)
    order = np.lexsort((point_idx, part_ids))
    return part_ids[order].astype(np.int64), point_idx[order]


def duplicate_points_grid(
    points: np.ndarray,
    cells: np.ndarray,
    inverse: np.ndarray,
    rects_int: np.ndarray,
    outer: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray]:
    """Grid-pruned eps-halo replication — same output as
    :func:`duplicate_points`, O(N + boundary) instead of O(P * N).

    A point can lie in partition p's outer rect (main grown by eps) only if
    p owns a cell in the 3x3 ring around the point's own 2eps cell: the
    eps-disk around any point of cell c stays inside c grown by eps, which
    the ring covers with an eps margin to spare. So candidates come from a
    cell -> owner lookup (9 per UNIQUE cell, not per point); the own cell's
    owner always contains the point (cell c main_p c outer_p, with eps
    margin dwarfing the snap function's worst-case ulp misassignment), and
    only ring candidates with a different owner take the exact
    outer-containment test — a boundary-band minority.

    Args:
      points: [N, >=2] float64.
      cells: [C, 2] int64 unique occupied cell indices (cell_histogram_int).
      inverse: [N] int64 row into `cells` per point.
      rects_int: [P, 4] integer partition rects in cell units (half-open:
        covering cells x..x2-1, y..y2-1).
      outer: [P, 4] float grown rects (binning.Margins.outer).

    Returns (part_ids [M], point_idx [M]) sorted by partition then point
    order — bit-identical to duplicate_points.
    """
    pts = np.asarray(points, dtype=np.float64)[:, :2]
    n = len(pts)
    rects_int = np.asarray(rects_int, dtype=np.int64).reshape(-1, 4)
    p_n = rects_int.shape[0]
    if p_n <= 1 or n == 0:
        return duplicate_points(pts, outer)
    grid_cells = (int(rects_int[:, 2].max()) - int(rects_int[:, 0].min())) * (
        int(rects_int[:, 3].max()) - int(rects_int[:, 1].min())
    )
    if grid_cells > 2**27:  # dense owner grid > 0.5 GB: sparse/huge-extent
        return duplicate_points(pts, outer)  # data; bounded-memory fallback

    gx0 = int(rects_int[:, 0].min())
    gy0 = int(rects_int[:, 1].min())
    gw = int(rects_int[:, 2].max()) - gx0
    gh = int(rects_int[:, 3].max()) - gy0
    owner = np.full((gw, gh), -1, dtype=np.int32)
    for p in range(p_n):
        x, y, x2, y2 = rects_int[p] - (gx0, gy0, gx0, gy0)
        owner[x:x2, y:y2] = p

    # Ring owners per UNIQUE cell. Neighbors are clamped to the grid: a
    # clamped lookup can only repeat an in-grid owner (dedup absorbs it);
    # out-of-grid cells are unowned so nothing is missed.
    cx = np.clip(cells[:, 0] - gx0, 0, gw - 1)
    cy = np.clip(cells[:, 1] - gy0, 0, gh - 1)
    own = owner[cx, cy]  # [C]; every occupied cell is owned
    ring = np.empty((len(cells), 8), dtype=np.int32)
    k = 0
    for dx in (-1, 0, 1):
        for dy in (-1, 0, 1):
            if dx == 0 and dy == 0:
                continue
            ring[:, k] = owner[
                np.clip(cx + dx, 0, gw - 1), np.clip(cy + dy, 0, gh - 1)
            ]
            k += 1
    # distinct foreign candidates per cell: sort the 8, drop repeats/own/-1
    ring.sort(axis=1)
    cand = (
        (ring >= 0)
        & (ring != own[:, None])
        & np.c_[np.ones(len(cells), bool), ring[:, 1:] != ring[:, :-1]]
    )
    ccell, ck = np.nonzero(cand)  # candidate (cell row, ring slot) pairs

    # Expand candidate (cell, partition) pairs to their points and run the
    # exact inclusive containment test (only boundary-band cells get here).
    part_base = own[inverse]  # [N] own-cell owner, in point order
    if ccell.size:
        cpart = ring[ccell, ck].astype(np.int64)
        grouped = _native.group_by_ints(inverse.astype(np.int32))
        if grouped is not None:
            # radix group-by doubles as the cell-sorted point order +
            # per-cell ranges (every histogram cell is occupied, so the
            # unique keys are exactly 0..C-1)
            _, _, per_cell, order_pts = grouped
            cstart = np.concatenate([[0], np.cumsum(per_cell)])
            nat = _native.halo_candidates(
                ccell, cpart, cstart, order_pts, pts, outer,
                int((cstart[ccell + 1] - cstart[ccell]).sum()),
            )
        else:
            nat = None
        if nat is not None:
            halo_part, halo_pt = nat
        else:
            order_pts = _native.argsort_ints(inverse.astype(np.int32))
            cstart = np.searchsorted(
                inverse[order_pts], np.arange(len(cells) + 1)
            )
            ccount = cstart[ccell + 1] - cstart[ccell]
            pt = order_pts[
                np.repeat(cstart[ccell], ccount)
                + (
                    np.arange(ccount.sum(), dtype=np.int64)
                    - np.repeat(np.cumsum(ccount) - ccount, ccount)
                )
            ]
            pp = np.repeat(cpart, ccount)
            hit = geo.contains_point(outer[pp], pts[pt])
            halo_part, halo_pt = pp[hit], pt[hit]
    else:
        halo_part = np.empty(0, np.int32)
        halo_pt = np.empty(0, np.int64)

    part_ids = np.concatenate([part_base.astype(np.int64), halo_part])
    point_idx = np.concatenate([np.arange(n, dtype=np.int64), halo_pt])
    okey = part_ids * n + point_idx
    order = _native.argsort_ints(
        okey.astype(np.int32) if p_n * n < 2**31 else okey
    )
    return part_ids[order], point_idx[order]


def _segment_indices(seg_starts: np.ndarray, seg_counts: np.ndarray) -> np.ndarray:
    """Concatenated index ranges [start, start+count) per segment —
    O(sum counts), the slice-based replacement for per-group O(M)
    membership scans in the packers."""
    total = int(seg_counts.sum())
    if total == 0:
        return np.empty(0, np.int64)
    off = np.repeat(seg_starts - (np.cumsum(seg_counts) - seg_counts), seg_counts)
    return np.arange(total, dtype=np.int64) + off


def _ratchet(floors, key, val: int, cap: int = None) -> int:
    """Monotone shape ratchet for streaming micro-batches: pin ``val``
    up to the largest value ever used under ``key`` (and remember the
    result). Data-dependent rungs fluctuate batch-to-batch across ladder
    boundaries, minting fresh jit signatures forever; the ratchet makes
    every pinned dimension monotone, so after warm-up each batch reuses
    EXACT shapes and steady-state compiles reach zero. ``cap`` bounds
    values that must not exceed a structural limit (slab <= bucket
    width). No-op when ``floors`` is None (batch runs)."""
    if floors is None:
        return val
    prev = int(floors.get(key, 0))
    v = max(int(val), prev)
    if cap is not None:
        v = min(v, int(cap))
    if prev and v > prev:
        # a post-warm-up floor raise mints a fresh jit signature — the
        # exact event a steady-state recompile storm is made of; the
        # counter lets obs/compile.py's storm warning (and the trace)
        # attribute a storm to the shape that kept moving
        obs.count("compiles.ratchet_raises")
        obs.event("binning.ratchet_raise", key=key, to=v)
    floors[key] = max(prev, v)
    return v


def _ladder_width(c: int, bucket_multiple: int) -> int:
    """Round a count up along a ~1.5x geometric ladder of bucket_multiple
    multiples (q in 1, 1.5, 2, 3, 4, 6, ... when it divides evenly): area
    waste bounded at ~2.25x worst-case vs exact, while widths recur across
    runs so the compile cache stays small."""
    c = max(1, int(c))
    q_needed = math.ceil(c / bucket_multiple)
    q = 1
    while q < q_needed:
        nq = q * 3 // 2 if (q & (q - 1)) == 0 else q * 4 // 3
        q = nq if nq > q else q + 1  # progress even at q=1
    return q * bucket_multiple


class BandedExtras(NamedTuple):
    """Cell-sorted block-slab metadata for the banded engine
    (dbscan_tpu/ops/banded.py). All arrays are indexed by SORTED position;
    B is a multiple of BANDED_BLOCK.

    fold_idx: [P_pad, B] int32 original fold index per position (identity on
    padding); rel_starts/spans: [P_pad, B, BANDED_ROWS] int32 per-point
    candidate runs (one per window cell row), starts relative to the row's
    block slab; slab_starts: [P_pad, B // BANDED_BLOCK, BANDED_ROWS] int32
    absolute slab origins; slab: static S >= every slab length (slab_start +
    S <= B); cx: [P_pad, B] int32 fine-grid cell column per position (for
    the device's window-slot arithmetic); cell_gid: [P_pad, B] int64 HOST
    array — global cell id per position (-1 padding), consumed by the
    cell-graph components pass, never shipped to the device.
    """

    fold_idx: np.ndarray
    rel_starts: np.ndarray
    spans: np.ndarray
    slab_starts: np.ndarray
    slab: int
    cx: np.ndarray
    cell_gid: np.ndarray


class BucketGroup(NamedTuple):
    """One same-width slab of partitions (see :func:`bucketize_grouped`).

    points: [P_pad, B, D]; mask: [P_pad, B] validity; point_idx: [P_pad, B]
    original row index (-1 padding); part_ids: [P_pad] ORIGINAL partition id
    per row, -1 on padding partitions. banded: window metadata when this
    group runs the banded engine (points then sit in cell-sorted order),
    None for the dense engine (fold order). row_counts: [P_pad] valid-slot
    count per row — valid slots are always the prefix 0..count-1, so the
    driver derives its instance maps arithmetically instead of scanning
    the [P_pad, B] masks.
    """

    points: np.ndarray
    mask: np.ndarray
    point_idx: np.ndarray
    part_ids: np.ndarray
    banded: BandedExtras = None
    row_counts: np.ndarray = None
    # CANONICAL banded emission ordinal (position in the deterministic
    # (width, win, partition-range) order), or None for dense groups.
    # Resumed runs may EMIT banded groups rotated (uncovered first, see
    # bucketize_banded resume_prefix) — checkpoint chunk identity keys on
    # this ordinal, not on arrival order.
    ordinal: int = None


def _pad_parts(n_sel: int, pad_parts_to: int, ladder: bool) -> int:
    """Partition-axis padding for one group: the exact mesh multiple by
    default, or a ladder width of it when the caller wants RECURRING
    group shapes (streaming micro-batches: a data-dependent partition
    count would mint a fresh jit signature per batch; the ladder bounds
    distinct shapes logarithmically at <= ~1.5x padded-partition waste)."""
    if ladder:
        return _ladder_width(max(1, n_sel), pad_parts_to)
    return max(1, math.ceil(n_sel / pad_parts_to) * pad_parts_to)


def bucketize_grouped(
    points: np.ndarray,
    part_ids: np.ndarray,
    point_idx: np.ndarray,
    n_parts: int,
    bucket_multiple: int = 128,
    pad_parts_to: int = 1,
    dtype=np.float32,
    on_group=None,
    pad_parts_ladder: bool = False,
    shape_floors=None,
    fill_payload: bool = True,
) -> Tuple[list, int]:
    """Pack partitions into SIZE-GROUPED static buffers.

    One global bucket width would make every partition pay the largest
    partition's O(B^2) sweep cost; here each partition's width is its count
    rounded up along a ~1.5x geometric ladder of ``bucket_multiple``
    multiples (1, 2, 3, 4, 6, 8, 12, ... x) — widths recur across runs so
    the compile cache stays bounded, with per-partition padding waste under
    2x (1.5x asymptotically; the ladder's first rung is 1 -> 2) — and
    partitions of equal width share one [P_g, B_g] slab. Total device work drops from P * B_max^2 toward
    sum(B_i^2). The group's partition axis pads to `pad_parts_to` (device
    count) with empty partitions, like bucketize.

    Returns (groups sorted by ascending width, max width).
    """
    pts = np.asarray(points)
    d = pts.shape[1]
    counts = np.bincount(part_ids, minlength=n_parts)

    widths = np.array(
        [_ladder_width(c, bucket_multiple) for c in counts], dtype=np.int64
    )
    starts = np.searchsorted(part_ids, np.arange(n_parts))
    slot_all = (
        np.arange(part_ids.size) - np.repeat(starts, counts)
        if part_ids.size
        else np.empty(0, np.int64)
    )

    groups = []
    max_b = 0
    for b in sorted(set(widths.tolist())):
        sel_parts = np.flatnonzero(widths == b)
        p_pad = _ratchet(
            shape_floors,
            ("gparts", int(b)),
            _pad_parts(len(sel_parts), pad_parts_to, pad_parts_ladder),
        )
        # resident-payload mode (fill_payload False): the device already
        # holds the full [N, D] row array, so the group ships only its
        # gather indices + mask — ~500x less upload for 512-d payloads
        buf = (
            np.zeros((p_pad, b, d), dtype=dtype) if fill_payload else None
        )
        mask = np.zeros((p_pad, b), dtype=bool)
        idx = np.full((p_pad, b), -1, dtype=np.int64)
        pid = np.full(p_pad, -1, dtype=np.int64)
        pid[: len(sel_parts)] = sel_parts
        if part_ids.size:
            # each partition's instances are one contiguous range of the
            # (partition-sorted) instance list: index by slices, NOT by an
            # O(M) membership scan per group (that made packing scale with
            # groups x instances)
            gi = _segment_indices(starts[sel_parts], counts[sel_parts])
            rows = np.repeat(np.arange(len(sel_parts)), counts[sel_parts])
            slots = slot_all[gi]
            if fill_payload:
                buf[rows, slots] = pts[point_idx[gi]].astype(dtype)
            mask[rows, slots] = True
            idx[rows, slots] = point_idx[gi]
        rc = np.zeros(p_pad, dtype=np.int64)
        rc[: len(sel_parts)] = counts[sel_parts]
        groups.append(BucketGroup(buf, mask, idx, pid, row_counts=rc))
        if on_group is not None:
            on_group(groups[-1])
        max_b = max(max_b, b)
    return groups, max_b


# Fine grid for the banded engine: cell side s = eps * FINE_CELL_FACTOR is
# chosen so that
#   (a) CLIQUE: any two points in one cell satisfy the device's distance
#       test — max intra-cell distance is s*sqrt(2) = eps*(1 - 1e-5), and
#       the 1e-5 margin dwarfs the f32 difference-form rounding (~1e-7
#       relative; cells are computed from the same f32-cast coordinates the
#       device measures). All cores of a cell therefore share ONE cluster,
#       which is what lets connected components run per-CELL on the host
#       instead of per-point on the device;
#   (b) REACH: any pair the device test accepts lies within +-2 cells on
#       each axis — acceptance implies lattice distance <= eps*(1+~1e-6),
#       and two cells reach 2s = 1.414*eps*(1-1e-5).
# bf16 is rejected upstream (driver): its ~4e-3 rounding swamps both margins.
FINE_CELL_FACTOR = (1.0 - 1e-5) / float(np.sqrt(2.0))

# Window geometry: candidate cells for a point are the 5x5 ring around its
# cell — BANDED_ROWS contiguous runs (one per cell row dy in [-2, 2]), each
# 5 cells wide. BANDED_WIN is the per-point cell-connectivity bitmask width
# (bit k*5+j = "some core in the window cell at (dy=k-2, dx=j-2) is
# eps-adjacent to this core point"); bit 12 is the point's own cell.
BANDED_ROWS = 5
BANDED_WIN = BANDED_ROWS * BANDED_ROWS

# At or above this width partitions route to the banded engine; below it
# the dense engine wins. Two forces meet here (both measured on v5e): a
# [B, B] f32 measure matrix no longer fits HBM at B = 65536 (16 GB), and
# below that width the banded path's fixed costs — two dispatch phases,
# the host cell-components round trip, the fine-grid packing — exceed the
# dense engine's whole single-launch runtime (~0.7s vs ~1.4-2.4s at
# 12k-32k widths) even though dense iterates its label propagation.
DENSE_MAX_BUCKET = 65536
# Spatial-path routing threshold, deliberately BELOW the hard width
# limit: a dense bucket between these widths is payable alone (a 49152
# tile is ~10 GB), but not alongside a banded pipeline's resident
# buffers on the same 16 GB chip — observed as TPU worker death at 100M
# points, where un-splittable single-cell pileups produce exactly such
# buckets next to hundreds of banded groups. Banded handles these
# widths at parity (measured 3.05 s banded vs 3.15 s dense-era routing
# at 1M/maxpp 32768), so spatial workloads route them banded. Paths
# with no spatial decomposition (cosine leaves, force-dense) still use
# the full DENSE_MAX_BUCKET limit — they run without a banded pipeline
# beside them.
BANDED_ROUTE_BUCKET = 32768

# Rows per block-slab tile in the banded engine; banded bucket widths are
# padded to a multiple of this. Bigger blocks amortize the per-slab DMA
# latency over more rows but widen the union slab S; with the fine grid a
# block spans ~4x more cells than the old eps-grid at equal occupancy, so
# the block is half the old 1024. Lives here (host side) so the packer has
# no jax dependency; dbscan_tpu/ops/banded.py imports it.
BANDED_BLOCK = 512


class CellGraphMeta(NamedTuple):
    """Host-side cell-graph metadata shared by every banded group of one
    train() call (cells are numbered globally across partitions).

    wintab: [U, BANDED_WIN] int32 — global cell id of each 5x5-window
      neighbor per cell (-1 where no occupied cell exists there); slot
      k*5+j is (dy=k-2, dx=j-2), slot 12 the cell itself. Edges never
      cross partitions (window keys carry the partition offset and are
      partition-verified).
    cell_part: [U] int32 partition id per cell.
    n_cells: U.
    """

    wintab: np.ndarray
    cell_part: np.ndarray
    n_cells: int


def bucketize_banded(
    points: np.ndarray,
    part_ids: np.ndarray,
    point_idx: np.ndarray,
    n_parts: int,
    eps: float,
    outer: np.ndarray,
    bucket_multiple: int = 128,
    pad_parts_to: int = 1,
    dtype=np.float32,
    force: bool = False,
    on_group=None,
    grid_points: np.ndarray = None,
    pad_parts_ladder: bool = False,
    resume_prefix: int = 0,
    on_plan=None,
    on_meta=None,
    shape_floors=None,
) -> Tuple[list, int, "CellGraphMeta"]:
    """Pack partitions for the banded engine (dbscan_tpu/ops/banded.py).

    Per partition: snap instances to the FINE grid (eps/sqrt(2) cells, see
    FINE_CELL_FACTOR), sort by cell row-major (stable, so equal-cell points
    keep fold order), and precompute each point's five contiguous candidate
    runs — one per window cell row — in the sorted order.

    ``grid_points``, when given, decouples the two coordinate systems: the
    fine grid, windows, and runs are built from ``grid_points`` [N, 2]
    (float64, no device cast — e.g. the equirectangular projection of
    spherical data, ops/sphere.py) while the device buffers carry
    ``points`` [N, D<=4] (e.g. 3-D chord coordinates) for the distance
    sweeps; ``eps`` then is the GRID-space scale (sphere.grid_eps), whose
    clique/reach margins versus the kernel threshold are the caller's
    contract. Without it, both roles fall to ``points`` and cells are
    computed from the f32-cast coordinates the device will see. Runs are grouped
    by blocks of BANDED_BLOCK consecutive rows: the per-(block, row) union
    of runs is the contiguous SLAB the device fetches with one
    dynamic_slice; the static slab bound S is the padded max slab length.
    Partitions below BANDED_ROUTE_BUCKET (unless ``force``) fall back to
    dense groups.

    Also numbers every occupied (partition, cell) pair globally and builds
    the 5x5 window-neighbor table the host cell-graph connected-components
    pass consumes (see dbscan_tpu/parallel/cellgraph.py).

    ``on_group``, when given, is invoked with each finished BucketGroup in
    emission order — the driver uses it to dispatch device work while later
    groups are still packing. ``on_meta``, when given, receives the
    CellGraphMeta BEFORE any group emits (cell numbering completes ahead
    of packing) — the driver's device cellcc finalize sizes its padded
    cell tables from it so per-chunk unpack dispatches can ride the
    packing window; never called on the all-dense early return.

    Returns (groups sorted with dense first, max width, CellGraphMeta);
    ``banded`` is set on the banded groups.
    """
    pts = np.asarray(points)
    gpts = None if grid_points is None else np.asarray(grid_points)
    if gpts is None:
        if pts.shape[1] != 2:
            raise ValueError(
                f"banded bucketing is 2-D only, got D={pts.shape[1]}"
            )
    else:
        if gpts.shape[1] != 2:
            raise ValueError(
                f"grid_points must be [N, 2], got D={gpts.shape[1]}"
            )
        if pts.shape[1] > 4:
            raise ValueError(
                "banded kernel payload is limited to D<=4 (difference-form "
                f"distance path), got D={pts.shape[1]}"
            )
    m_tot = part_ids.size
    counts = np.bincount(part_ids, minlength=n_parts)
    part_start = np.concatenate([[0], np.cumsum(counts)])[:-1]
    widths_b = np.array(
        [_ladder_width(c, bucket_multiple) for c in counts], dtype=np.int64
    )

    empty_meta = CellGraphMeta(
        np.empty((0, BANDED_WIN), np.int32), np.empty(0, np.int32), 0
    )
    widths_band_all = (widths_b + BANDED_BLOCK - 1) // BANDED_BLOCK * BANDED_BLOCK
    if m_tot == 0 or not (
        force or bool((widths_band_all >= BANDED_ROUTE_BUCKET).any())
    ):
        # nothing will route banded: skip the whole fine-grid pass
        groups, max_b = bucketize_grouped(
            points, part_ids, point_idx, n_parts, bucket_multiple,
            pad_parts_to, dtype, on_group=on_group,
            pad_parts_ladder=pad_parts_ladder,
        )
        return groups, max_b, empty_meta

    cell = float(eps) * FINE_CELL_FACTOR
    # Cells must be computed from the coordinates the DEVICE sees: under f32
    # the cast can move a point across a float64 cell boundary (quantization
    # error scales with |coordinate|, far beyond the arithmetic-rounding
    # margins), and a run built from the float64 cell would miss pairs the
    # device's distance test accepts.
    inv_cell = 1.0 / cell
    # one contiguous float64 view shared by every native call below (the
    # wrappers' ascontiguousarray then no-ops instead of copying per group)
    pts64 = (
        np.ascontiguousarray(pts, dtype=np.float64)
        if dtype in (np.float32, np.float64)
        else None
    )
    # grid source: the payload coordinates themselves (f32-cast to match
    # the device) or the separate grid projection (f64, never cast — the
    # device measures in a different coordinate system entirely)
    grid64 = (
        pts64 if gpts is None else np.ascontiguousarray(gpts, np.float64)
    )
    native = (
        _native.fine_cells(
            grid64, point_idx, part_ids, outer, inv_cell, n_parts,
            dtype == np.float32 and gpts is None,
        )
        if pts64 is not None
        else None
    )
    if native is not None:
        # fused pass: cast + snap + per-partition maxima in one sweep; the
        # group packer below reads coordinates straight from `pts` with the
        # same cast, so the [M, 2] device-dtype gather disappears entirely
        cx, cy, cxmax, cymax = native
        xy_store = None
    else:
        # Cast the whole [N, D] input once and gather in the device dtype —
        # the gathered array IS the group-buffer payload, so the per-group
        # astype disappears too.
        xy_store = np.asarray(pts, dtype=dtype)[point_idx]
        if gpts is None:
            xy_dev = xy_store.astype(np.float64)
        else:
            xy_dev = np.asarray(gpts, dtype=np.float64)[point_idx]
        ox = outer[part_ids, 0]
        oy = outer[part_ids, 1]
        cx = np.maximum(
            np.floor((xy_dev[:, 0] - ox) * inv_cell), 0.0
        ).astype(np.int64)
        cy = np.maximum(
            np.floor((xy_dev[:, 1] - oy) * inv_cell), 0.0
        ).astype(np.int64)

        # Segment maxima via reduceat (instances are sorted by partition).
        nz = counts > 0
        segs = part_start[nz]
        cxmax = np.zeros(n_parts, dtype=np.int64)
        cymax = np.zeros(n_parts, dtype=np.int64)
        if segs.size:
            cxmax[nz] = np.maximum.reduceat(cx, segs)
            cymax[nz] = np.maximum.reduceat(cy, segs)
    stride = cxmax + 5  # cx + 4 < stride: row windows never wrap
    big = int((stride * (cymax + 3)).max()) + 1  # per-partition key space
    gkey = part_ids * big + cy * stride[part_ids] + cx

    # Stable sort by (partition, cell key): instances arrive in (partition,
    # fold) order, so ties keep fold order inside each cell. Stable argsort
    # on one packed integer key radix-sorts in O(M); int32 keys when they fit.
    if n_parts * big < np.iinfo(np.int32).max:
        gkey = gkey.astype(np.int32)
    order = _native.argsort_ints(gkey)
    gkey_s = gkey[order]
    cx_s = cx[order]
    if native is None:
        p_s = part_ids[order]
        fold_s = (order - part_start[p_s]).astype(np.int64)
        ptidx_s = point_idx[order]
        xy_s = xy_store[order]
        slots_s = np.arange(m_tot, dtype=np.int64) - part_start[p_s]

    # Unique occupied cells (globally numbered: sorted by partition then
    # row-major key) and per-instance cell rank.
    newcell = np.r_[True, gkey_s[1:] != gkey_s[:-1]]
    cell_first = np.flatnonzero(newcell)  # [U] first sorted pos per cell
    ukey = gkey_s[cell_first].astype(np.int64)  # [U]
    cell_rank = np.cumsum(newcell) - 1  # [M] global cell id per instance
    upart = part_ids[order[cell_first]]
    ustride = stride[upart]
    useg_start = part_start[upart]
    useg_end = useg_start + counts[upart]
    cell_pos = np.r_[cell_first, m_tot]  # [U+1] cell -> first sorted pos
    u_n = len(ukey)

    # Run boundaries per UNIQUE cell (instances in a cell share them): the
    # run for window row dy spans cell keys [key + dy*stride - 2,
    # key + dy*stride + 2]. Out-of-grid rows resolve to empty runs via the
    # segment clamps (no cell carries their key inside the segment; key-
    # space headroom keeps row overflow inside this partition's range).
    # Everything below stays in UNIQUE-CELL space (U entries) as long as
    # possible — per-instance [M, 5] intermediates at 10M+ points dominated
    # this function's runtime before.
    ustarts = np.empty((u_n, BANDED_ROWS), dtype=np.int32)
    uspans = np.empty((u_n, BANDED_ROWS), dtype=np.int32)
    si_c = np.empty((u_n, BANDED_ROWS), dtype=np.int64)  # cell-space run
    ei_c = np.empty((u_n, BANDED_ROWS), dtype=np.int64)  # bounds, for wintab
    for k, dr in enumerate((-2, -1, 0, 1, 2)):
        lo = ukey + dr * ustride - 2
        si = np.searchsorted(ukey, lo)
        ei = np.searchsorted(ukey, lo + 5)
        si_c[:, k] = si
        ei_c[:, k] = ei
        s = np.clip(cell_pos[si], useg_start, useg_end)
        e = np.clip(cell_pos[ei], s, useg_end)
        ustarts[:, k] = s - useg_start
        uspans[:, k] = e - s

    # 5x5 window-neighbor cell table for the host cell graph, recovered
    # from the run bounds by GATHER (the cells of run k are consecutive
    # unique-cell indices si..ei-1 with keys in [lo, lo+5)): ~10x cheaper
    # than 25 searchsorted passes. A run can alias into a NEIGHBORING
    # partition's key space when the window pokes past the grid edge, so a
    # hit requires both the in-window offset and the same partition.
    wintab = np.full((u_n, BANDED_WIN), -1, dtype=np.int32)
    off5 = np.arange(5, dtype=np.int64)
    for k, dr in enumerate((-2, -1, 0, 1, 2)):
        lo = ukey + dr * ustride - 2
        idx = si_c[:, k, None] + off5[None, :]  # [U, 5] candidate cells
        inrun = idx < ei_c[:, k, None]
        idx_c = np.minimum(idx, u_n - 1)
        offs = ukey[idx_c] - lo[:, None]
        ok = (
            inrun
            & (offs >= 0)
            & (offs < 5)
            & (upart[idx_c] == upart[:, None])
        )
        rr, cc = np.nonzero(ok)
        wintab[rr, k * 5 + offs[rr, cc]] = idx_c[rr, cc].astype(np.int32)
    meta = CellGraphMeta(wintab, upart.astype(np.int32), u_n)
    if on_meta is not None:
        on_meta(meta)

    # Banded bucket widths: the dense ladder width padded up to a multiple
    # of the block size.
    t = BANDED_BLOCK
    widths_band = (widths_b + t - 1) // t * t
    if shape_floors is not None:
        # Uniform streaming width: banded-eligible partitions all share
        # ONE ratcheted width class. Per-partition ladder widths
        # fluctuate across micro-batches (49152 <-> 65536 at the top
        # rungs), and every distinct width mints its own phase-1
        # signature AND a distinct chunk-postpass group-shape multiset —
        # the combinatorial compile source the ratchet alone cannot pin.
        # Costs bounded masked padding (<= the ladder step, ~1.33x) in
        # exchange for a single recurring signature family.
        eligible = (widths_b > 0) & (
            force | (widths_band >= BANDED_ROUTE_BUCKET)
        )
        if eligible.any():
            uw = _ratchet(
                shape_floors, "buw", int(widths_band[eligible].max())
            )
            widths_band = np.where(eligible, uw, widths_band)
    nb_of = widths_band // t  # blocks per partition
    maxnb = int(nb_of.max())

    # Per-(partition block, window row) slab = union of the block rows'
    # runs, computed per CELL x spanned-block (a cell's instances are a
    # contiguous slot range, so it touches ceil(len/t)+1 blocks; total
    # expansion ~ U + number of blocks, not M).
    n_bkeys = n_parts * maxnb
    slot0 = cell_pos[:-1] - useg_start  # [U] first slot of cell
    slot1 = cell_pos[1:] - 1 - useg_start  # [U] last slot (cells nonempty)
    b0 = slot0 // t
    nspan = slot1 // t - b0 + 1
    rows_e = np.repeat(np.arange(u_n), nspan)
    boff = np.arange(len(rows_e), dtype=np.int64) - np.repeat(
        np.cumsum(nspan) - nspan, nspan
    )
    bkey_e = upart[rows_e] * maxnb + b0[rows_e] + boff  # nondecreasing
    bmin = np.zeros((n_bkeys, BANDED_ROWS), dtype=np.int64)
    bmax = np.zeros((n_bkeys, BANDED_ROWS), dtype=np.int64)
    uvalid = uspans > 0
    for k in range(BANDED_ROWS):
        v = uvalid[rows_e, k]
        bk = bkey_e[v]
        if bk.size == 0:
            continue
        st = ustarts[rows_e[v], k].astype(np.int64)
        first = np.flatnonzero(np.r_[True, bk[1:] != bk[:-1]])
        u = bk[first]
        bmin[u, k] = np.minimum.reduceat(st, first)
        bmax[u, k] = np.maximum.reduceat(
            st + uspans[rows_e[v], k], first
        )

    slab_need = (bmax - bmin).max(axis=1).reshape(n_parts, maxnb).max(axis=1)
    win = np.minimum(
        np.array([_ladder_width(s, 128) for s in slab_need], dtype=np.int64),
        widths_band,  # slab can never exceed the bucket; ladder may overshoot
    )
    if shape_floors is not None:
        # per-width slab pin (slab is part of the (width, slab) group
        # class AND a static jit arg of the phase-1 executor): ratchet it
        # so micro-batch density fluctuations stop re-minting signatures
        for i in range(n_parts):
            win[i] = _ratchet(
                shape_floors,
                ("slab", int(widths_band[i])),
                int(win[i]),
                cap=int(widths_band[i]),
            )

    # Clamp slab origins so slab_start + S <= B; runs still fit (a clamped
    # origin only moves left, and run ends are bounded by the bucket width).
    part_of_bkey = np.repeat(np.arange(n_parts), maxnb)
    sstart = np.clip(bmin, 0, (widths_band - win)[part_of_bkey][:, None])

    use_banded = (counts > 0) & (force | (widths_band >= BANDED_ROUTE_BUCKET))

    # run tables ship as uint16 whenever every slab bound fits (starts are
    # slab-relative < S, spans <= S): half the largest host->device upload;
    # banded_phase1 widens to int32 after transfer. One run-wide choice so
    # every group shares one jit signature.
    run_dtype = (
        np.uint16
        if not use_banded.any() or int(win[use_banded].max()) < 2**16
        else np.int32
    )

    groups: list = []
    max_b = 0

    # Dense fallback partitions go through the plain packer. Instances of
    # banded partitions are filtered out but n_parts keeps original ids;
    # the resulting zero-count rows land in the smallest-width group with
    # all-False masks and are skipped by the driver's instance scan.
    if not use_banded.all():
        dense_inst = ~use_banded[part_ids]
        if dense_inst.any() or not use_banded.any():
            dgroups, dmax = bucketize_grouped(
                points,
                part_ids[dense_inst],
                point_idx[dense_inst],
                n_parts,
                bucket_multiple,
                pad_parts_to,
                dtype,
                on_group=on_group,
                pad_parts_ladder=pad_parts_ladder,
            )
            groups.extend(dgroups)
            max_b = max(max_b, dmax)

    sstart32 = sstart.astype(np.int32)
    # Cap the slots per emitted group: one (width, win) class at 100M
    # scale would otherwise pack into a single enormous group, making the
    # group both the dispatch unit AND the compact-chunk/checkpoint
    # granularity — minutes of continuous device work before the first
    # restart point can even form (the round-3 worker-endurance campaign
    # failed exactly there, zero chunks saved). Splitting a class into
    # slot-bounded groups keeps jit signatures shared (same b/w), bounds
    # the per-dispatch HBM residency, and lets retry loops shrink the
    # restart granularity with DBSCAN_GROUP_SLOTS alongside
    # DBSCAN_COMPACT_CHUNK_SLOTS. Labels are group-batching independent
    # (cell ids are global; the postpass and finalize are per-partition).
    group_slot_cap = int(config.env("DBSCAN_GROUP_SLOTS"))
    # Canonical emission plan: deterministic (width, win, partition-range)
    # order. The canonical ORDINAL of each entry — not arrival order — is
    # what the driver's chunk-checkpoint signatures key on, so a resumed
    # run may emit a ROTATION of this plan: the checkpoint-covered prefix
    # [0, resume_prefix) packs LAST (its device work is skipped anyway)
    # and uncovered groups reach the device within seconds of the fine-
    # grid pass instead of minutes — the difference between a retry leg
    # landing a new restart point and dying during re-pack (the 100M
    # campaign's observed failure mode on a degraded worker).
    plan = []
    for b, w in sorted(
        set(zip(widths_band[use_banded].tolist(), win[use_banded].tolist()))
    ):
        sel_class = np.flatnonzero(
            use_banded & (widths_band == b) & (win == w)
        )
        per_group = max(1, group_slot_cap // b)
        if per_group > pad_parts_to:  # align to the mesh pad where possible
            per_group = per_group // pad_parts_to * pad_parts_to
        for s0 in range(0, len(sel_class), per_group):
            plan.append((b, w, sel_class[s0 : s0 + per_group]))
    if on_plan is not None:
        # the full canonical plan, BEFORE any packing: (padded partition
        # count, bucket width) per banded group — enough for a caller to
        # pre-compute chunk-checkpoint totals (slots = p_pad * b) minutes
        # before the first restart point could land
        on_plan(
            [
                (_pad_parts(len(sp_), pad_parts_to, pad_parts_ladder), b)
                for b, _w, sp_ in plan
            ]
        )
    emit = list(range(len(plan)))
    if resume_prefix:
        rp_ = min(int(resume_prefix), len(plan))
        emit = emit[rp_:] + emit[:rp_]
    for k in emit:
        b, w, sel_parts = plan[k]
        nb = b // t
        p_pad = _ratchet(
            shape_floors,
            ("bparts", int(b), int(w)),
            _pad_parts(len(sel_parts), pad_parts_to, pad_parts_ladder),
        )
        pid = np.full(p_pad, -1, dtype=np.int64)
        pid[: len(sel_parts)] = sel_parts
        sl_b = np.zeros((p_pad, nb, BANDED_ROWS), dtype=np.int32)
        sl_b[: len(sel_parts)] = sstart[
            sel_parts[:, None] * maxnb + np.arange(nb)[None, :]
        ]
        packed = (
            _native.pack_banded_group(
                sel_parts, p_pad, part_start, counts, order, pts64,
                point_idx, cx_s, cell_rank, ustarts, uspans, sstart32,
                maxnb, t, b, dtype, run_dtype, d_out=pts.shape[1],
            )
            if native is not None
            else None
        )
        if packed is not None:
            buf, mask, idx, fold_b, st_b, sp_b, cx_b, cgid_b = packed
        else:
            buf = np.zeros((p_pad, b, pts.shape[1]), dtype=dtype)
            mask = np.zeros((p_pad, b), dtype=bool)
            idx = np.full((p_pad, b), -1, dtype=np.int64)
            iota = np.arange(b, dtype=np.int32)
            fold_b = np.broadcast_to(iota, (p_pad, b)).copy()
            st_b = np.zeros((p_pad, b, BANDED_ROWS), dtype=run_dtype)
            sp_b = np.zeros((p_pad, b, BANDED_ROWS), dtype=run_dtype)
            cx_b = np.zeros((p_pad, b), dtype=np.int32)
            cgid_b = np.full((p_pad, b), -1, dtype=np.int64)

            # slice each partition's contiguous instance range (instances
            # are partition-sorted) — no O(M) membership scan per group
            gi = _segment_indices(part_start[sel_parts], counts[sel_parts])
            rows = np.repeat(np.arange(len(sel_parts)), counts[sel_parts])
            slots = slots_s[gi]
            buf[rows, slots] = xy_s[gi]
            mask[rows, slots] = True
            idx[rows, slots] = ptidx_s[gi]
            fold_b[rows, slots] = fold_s[gi]
            # Per-instance run start within its slab (invalid runs pin to
            # 0 rather than inheriting a meaningless negative offset);
            # gathered from unique-cell space for this group's instances.
            cr = cell_rank[gi]
            sp_i = uspans[cr]
            st_i = ustarts[cr] - sstart32[p_s[gi] * maxnb + slots_s[gi] // t]
            st_b[rows, slots] = np.where(sp_i > 0, st_i, 0)
            sp_b[rows, slots] = sp_i
            cx_b[rows, slots] = cx_s[gi]
            cgid_b[rows, slots] = cell_rank[gi]

        rc = np.zeros(p_pad, dtype=np.int64)
        rc[: len(sel_parts)] = counts[sel_parts]
        groups.append(
            BucketGroup(
                buf, mask, idx, pid,
                BandedExtras(fold_b, st_b, sp_b, sl_b, int(w), cx_b, cgid_b),
                row_counts=rc,
                ordinal=k,
            )
        )
        if on_group is not None:
            on_group(groups[-1])
        max_b = max(max_b, b)
    return groups, max_b, meta
