"""Host-side halo binning: margins, eps-halo duplication, static bucketing.

This is the TPU replacement for the reference's broadcast + shuffle stages
(DBSCAN.scala:116-152): instead of shipping margin lists to executors and
shuffling points through groupByKey, the host computes margins, replicates
each point into every partition whose grown rectangle contains it, and packs
the result into STATIC [P, B, ...] device buffers (padding + mask) so one
compiled kernel handles every partition — no dynamic shapes under jit.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Tuple

import numpy as np

from dbscan_tpu.ops import geometry as geo


class Margins(NamedTuple):
    """Per-partition (inner, main, outer) float rects, the reference's
    Margins triple (DBSCAN.scala:70, :116-121): inner = main shrunk by eps,
    outer = main grown by eps."""

    inner: np.ndarray  # [P, 4]
    main: np.ndarray  # [P, 4]
    outer: np.ndarray  # [P, 4]


class Buckets(NamedTuple):
    """Static device buffers for the partition fan-out.

    points: [P_pad, B, D] float; rows beyond a partition's count are zero.
    mask: [P_pad, B] bool validity.
    point_idx: [P_pad, B] int64 original row index, -1 on padding.
    n_parts: true number of partitions (P_pad may include empty padding
      partitions so the leading axis divides the mesh).
    """

    points: np.ndarray
    mask: np.ndarray
    point_idx: np.ndarray
    n_parts: int


def build_margins(rects_int: np.ndarray, cell_size: float, eps: float) -> Margins:
    """Margins from integer partition rects (DBSCAN.scala:116-121)."""
    main = geo.int_rects_to_float(np.asarray(rects_int).reshape(-1, 4), cell_size)
    return Margins(
        inner=geo.shrink(main, eps), main=main, outer=geo.shrink(main, -eps)
    )


def duplicate_points(
    points: np.ndarray, outer: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """eps-halo replication: every (partition, point) pair with
    outer.contains(point) (DBSCAN.scala:132-137), vectorized and chunked over
    points. Returns (part_ids [M], point_idx [M]) sorted by partition then
    point order."""
    pts = np.asarray(points, dtype=np.float64)[:, :2]
    P = outer.shape[0]
    part_ids = []
    point_idx = []
    # bound the [P, chunk] bool intermediate regardless of partition count
    chunk = max(1, int(2**24 // max(1, P)))
    for s in range(0, len(pts), chunk):
        c = pts[s : s + chunk]
        inside = geo.contains_point(outer[:, None, :], c[None, :, :])  # [P, nc]
        p, i = np.nonzero(inside)
        part_ids.append(p)
        point_idx.append(i + s)
    part_ids = np.concatenate(part_ids) if part_ids else np.empty(0, np.int64)
    point_idx = np.concatenate(point_idx) if point_idx else np.empty(0, np.int64)
    order = np.lexsort((point_idx, part_ids))
    return part_ids[order].astype(np.int64), point_idx[order]


def bucketize(
    points: np.ndarray,
    part_ids: np.ndarray,
    point_idx: np.ndarray,
    n_parts: int,
    bucket_multiple: int = 128,
    pad_parts_to: int = 1,
    dtype=np.float32,
) -> Buckets:
    """Pack duplicated points into static [P_pad, B, D] buffers.

    B is the max per-partition count rounded up to `bucket_multiple` (bounds
    recompilation across runs: kernels specialize on B, not exact counts).
    P_pad rounds the partition axis up to a multiple of `pad_parts_to`
    (device count) with empty partitions.
    """
    pts = np.asarray(points)
    d = pts.shape[1]
    counts = np.bincount(part_ids, minlength=n_parts)
    max_count = int(counts.max()) if counts.size else 0
    b = max(bucket_multiple, math.ceil(max(1, max_count) / bucket_multiple) * bucket_multiple)
    p_pad = max(1, math.ceil(n_parts / pad_parts_to) * pad_parts_to)

    buf = np.zeros((p_pad, b, d), dtype=dtype)
    mask = np.zeros((p_pad, b), dtype=bool)
    idx = np.full((p_pad, b), -1, dtype=np.int64)

    if part_ids.size:
        # part_ids is sorted; slot = position within its partition group
        starts = np.searchsorted(part_ids, np.arange(n_parts))
        slot = np.arange(part_ids.size) - np.repeat(starts, counts)
        buf[part_ids, slot] = pts[point_idx].astype(dtype)
        mask[part_ids, slot] = True
        idx[part_ids, slot] = point_idx
    return Buckets(points=buf, mask=mask, point_idx=idx, n_parts=n_parts)
