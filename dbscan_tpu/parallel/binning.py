"""Host-side halo binning: margins, eps-halo duplication, static bucketing.

This is the TPU replacement for the reference's broadcast + shuffle stages
(DBSCAN.scala:116-152): instead of shipping margin lists to executors and
shuffling points through groupByKey, the host computes margins, replicates
each point into every partition whose grown rectangle contains it, and packs
the result into STATIC [P, B, ...] device buffers (padding + mask) so one
compiled kernel handles every partition — no dynamic shapes under jit.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Tuple

import numpy as np

from dbscan_tpu.ops import geometry as geo


class Margins(NamedTuple):
    """Per-partition (inner, main, outer) float rects, the reference's
    Margins triple (DBSCAN.scala:70, :116-121): inner = main shrunk by eps,
    outer = main grown by eps."""

    inner: np.ndarray  # [P, 4]
    main: np.ndarray  # [P, 4]
    outer: np.ndarray  # [P, 4]


def build_margins(rects_int: np.ndarray, cell_size: float, eps: float) -> Margins:
    """Margins from integer partition rects (DBSCAN.scala:116-121)."""
    main = geo.int_rects_to_float(np.asarray(rects_int).reshape(-1, 4), cell_size)
    return Margins(
        inner=geo.shrink(main, eps), main=main, outer=geo.shrink(main, -eps)
    )


def duplicate_points(
    points: np.ndarray, outer: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """eps-halo replication: every (partition, point) pair with
    outer.contains(point) (DBSCAN.scala:132-137), vectorized and chunked over
    points. Returns (part_ids [M], point_idx [M]) sorted by partition then
    point order."""
    pts = np.asarray(points, dtype=np.float64)[:, :2]
    P = outer.shape[0]
    part_ids = []
    point_idx = []
    # bound the [P, chunk] bool intermediate regardless of partition count
    chunk = max(1, int(2**24 // max(1, P)))
    for s in range(0, len(pts), chunk):
        c = pts[s : s + chunk]
        inside = geo.contains_point(outer[:, None, :], c[None, :, :])  # [P, nc]
        p, i = np.nonzero(inside)
        part_ids.append(p)
        point_idx.append(i + s)
    part_ids = np.concatenate(part_ids) if part_ids else np.empty(0, np.int64)
    point_idx = np.concatenate(point_idx) if point_idx else np.empty(0, np.int64)
    order = np.lexsort((point_idx, part_ids))
    return part_ids[order].astype(np.int64), point_idx[order]


class BucketGroup(NamedTuple):
    """One same-width slab of partitions (see :func:`bucketize_grouped`).

    points: [P_pad, B, D]; mask: [P_pad, B] validity; point_idx: [P_pad, B]
    original row index (-1 padding); part_ids: [P_pad] ORIGINAL partition id
    per row, -1 on padding partitions.
    """

    points: np.ndarray
    mask: np.ndarray
    point_idx: np.ndarray
    part_ids: np.ndarray


def bucketize_grouped(
    points: np.ndarray,
    part_ids: np.ndarray,
    point_idx: np.ndarray,
    n_parts: int,
    bucket_multiple: int = 128,
    pad_parts_to: int = 1,
    dtype=np.float32,
) -> Tuple[list, int]:
    """Pack partitions into SIZE-GROUPED static buffers.

    One global bucket width would make every partition pay the largest
    partition's O(B^2) sweep cost; here each partition's width is its count
    rounded up along a ~1.5x geometric ladder of ``bucket_multiple``
    multiples (1, 2, 3, 4, 6, 8, 12, ... x) — widths recur across runs so
    the compile cache stays bounded, with per-partition padding waste under
    2x (1.5x asymptotically; the ladder's first rung is 1 -> 2) — and
    partitions of equal width share one [P_g, B_g] slab. Total device work drops from P * B_max^2 toward
    sum(B_i^2). The group's partition axis pads to `pad_parts_to` (device
    count) with empty partitions, like bucketize.

    Returns (groups sorted by ascending width, max width).
    """
    pts = np.asarray(points)
    d = pts.shape[1]
    counts = np.bincount(part_ids, minlength=n_parts)

    def width(c: int) -> int:
        # 1.5x geometric ladder of bucket_multiple multiples
        # (q in 1, 1.5, 2, 3, 4, 6, ... when it divides evenly): area waste
        # bounded at ~2.25x worst-case vs exact, while widths recur across
        # runs so the compile cache stays small.
        c = max(1, int(c))
        q_needed = math.ceil(c / bucket_multiple)
        q = 1
        while q < q_needed:
            nq = q * 3 // 2 if (q & (q - 1)) == 0 else q * 4 // 3
            q = nq if nq > q else q + 1  # progress even at q=1
        return q * bucket_multiple

    widths = np.array([width(c) for c in counts], dtype=np.int64)
    starts = np.searchsorted(part_ids, np.arange(n_parts))
    slot_all = (
        np.arange(part_ids.size) - np.repeat(starts, counts)
        if part_ids.size
        else np.empty(0, np.int64)
    )

    groups = []
    max_b = 0
    for b in sorted(set(widths.tolist())):
        sel_parts = np.flatnonzero(widths == b)
        p_pad = max(1, math.ceil(len(sel_parts) / pad_parts_to) * pad_parts_to)
        buf = np.zeros((p_pad, b, d), dtype=dtype)
        mask = np.zeros((p_pad, b), dtype=bool)
        idx = np.full((p_pad, b), -1, dtype=np.int64)
        pid = np.full(p_pad, -1, dtype=np.int64)
        pid[: len(sel_parts)] = sel_parts
        if part_ids.size:
            row_of_part = np.full(n_parts, -1, dtype=np.int64)
            row_of_part[sel_parts] = np.arange(len(sel_parts))
            in_group = row_of_part[part_ids] >= 0
            gi = np.flatnonzero(in_group)
            rows = row_of_part[part_ids[gi]]
            slots = slot_all[gi]
            buf[rows, slots] = pts[point_idx[gi]].astype(dtype)
            mask[rows, slots] = True
            idx[rows, slots] = point_idx[gi]
        groups.append(BucketGroup(buf, mask, idx, pid))
        max_b = max(max_b, b)
    return groups, max_b
