"""Host-side halo binning: margins, eps-halo duplication, static bucketing.

This is the TPU replacement for the reference's broadcast + shuffle stages
(DBSCAN.scala:116-152): instead of shipping margin lists to executors and
shuffling points through groupByKey, the host computes margins, replicates
each point into every partition whose grown rectangle contains it, and packs
the result into STATIC [P, B, ...] device buffers (padding + mask) so one
compiled kernel handles every partition — no dynamic shapes under jit.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Tuple

import numpy as np

from dbscan_tpu.ops import geometry as geo


class Margins(NamedTuple):
    """Per-partition (inner, main, outer) float rects, the reference's
    Margins triple (DBSCAN.scala:70, :116-121): inner = main shrunk by eps,
    outer = main grown by eps."""

    inner: np.ndarray  # [P, 4]
    main: np.ndarray  # [P, 4]
    outer: np.ndarray  # [P, 4]


def build_margins(rects_int: np.ndarray, cell_size: float, eps: float) -> Margins:
    """Margins from integer partition rects (DBSCAN.scala:116-121)."""
    main = geo.int_rects_to_float(np.asarray(rects_int).reshape(-1, 4), cell_size)
    return Margins(
        inner=geo.shrink(main, eps), main=main, outer=geo.shrink(main, -eps)
    )


def duplicate_points(
    points: np.ndarray, outer: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """eps-halo replication: every (partition, point) pair with
    outer.contains(point) (DBSCAN.scala:132-137), vectorized and chunked over
    points. Returns (part_ids [M], point_idx [M]) sorted by partition then
    point order."""
    pts = np.asarray(points, dtype=np.float64)[:, :2]
    P = outer.shape[0]
    part_ids = []
    point_idx = []
    # bound the [P, chunk] bool intermediate regardless of partition count
    chunk = max(1, int(2**24 // max(1, P)))
    for s in range(0, len(pts), chunk):
        c = pts[s : s + chunk]
        inside = geo.contains_point(outer[:, None, :], c[None, :, :])  # [P, nc]
        p, i = np.nonzero(inside)
        part_ids.append(p)
        point_idx.append(i + s)
    part_ids = np.concatenate(part_ids) if part_ids else np.empty(0, np.int64)
    point_idx = np.concatenate(point_idx) if point_idx else np.empty(0, np.int64)
    order = np.lexsort((point_idx, part_ids))
    return part_ids[order].astype(np.int64), point_idx[order]


def _ladder_width(c: int, bucket_multiple: int) -> int:
    """Round a count up along a ~1.5x geometric ladder of bucket_multiple
    multiples (q in 1, 1.5, 2, 3, 4, 6, ... when it divides evenly): area
    waste bounded at ~2.25x worst-case vs exact, while widths recur across
    runs so the compile cache stays small."""
    c = max(1, int(c))
    q_needed = math.ceil(c / bucket_multiple)
    q = 1
    while q < q_needed:
        nq = q * 3 // 2 if (q & (q - 1)) == 0 else q * 4 // 3
        q = nq if nq > q else q + 1  # progress even at q=1
    return q * bucket_multiple


class BandedExtras(NamedTuple):
    """Cell-sorted block-slab metadata for the banded engine
    (dbscan_tpu/ops/banded.py). All arrays are indexed by SORTED position;
    B is a multiple of ops.banded.BANDED_BLOCK.

    fold_idx: [P_pad, B] int32 original fold index per position (identity on
    padding); pos_of_fold: [P_pad, B] int32 inverse permutation;
    rel_starts/spans: [P_pad, B, 3] int32 per-point candidate runs (one per
    neighboring cell row), starts relative to the row's block slab;
    slab_starts: [P_pad, B // BANDED_BLOCK, 3] int32 absolute slab origins;
    slab: static S >= every slab length (slab_start + S <= B).
    """

    fold_idx: np.ndarray
    pos_of_fold: np.ndarray
    rel_starts: np.ndarray
    spans: np.ndarray
    slab_starts: np.ndarray
    slab: int


class BucketGroup(NamedTuple):
    """One same-width slab of partitions (see :func:`bucketize_grouped`).

    points: [P_pad, B, D]; mask: [P_pad, B] validity; point_idx: [P_pad, B]
    original row index (-1 padding); part_ids: [P_pad] ORIGINAL partition id
    per row, -1 on padding partitions. banded: window metadata when this
    group runs the banded engine (points then sit in cell-sorted order),
    None for the dense engine (fold order).
    """

    points: np.ndarray
    mask: np.ndarray
    point_idx: np.ndarray
    part_ids: np.ndarray
    banded: BandedExtras = None


def bucketize_grouped(
    points: np.ndarray,
    part_ids: np.ndarray,
    point_idx: np.ndarray,
    n_parts: int,
    bucket_multiple: int = 128,
    pad_parts_to: int = 1,
    dtype=np.float32,
) -> Tuple[list, int]:
    """Pack partitions into SIZE-GROUPED static buffers.

    One global bucket width would make every partition pay the largest
    partition's O(B^2) sweep cost; here each partition's width is its count
    rounded up along a ~1.5x geometric ladder of ``bucket_multiple``
    multiples (1, 2, 3, 4, 6, 8, 12, ... x) — widths recur across runs so
    the compile cache stays bounded, with per-partition padding waste under
    2x (1.5x asymptotically; the ladder's first rung is 1 -> 2) — and
    partitions of equal width share one [P_g, B_g] slab. Total device work drops from P * B_max^2 toward
    sum(B_i^2). The group's partition axis pads to `pad_parts_to` (device
    count) with empty partitions, like bucketize.

    Returns (groups sorted by ascending width, max width).
    """
    pts = np.asarray(points)
    d = pts.shape[1]
    counts = np.bincount(part_ids, minlength=n_parts)

    widths = np.array(
        [_ladder_width(c, bucket_multiple) for c in counts], dtype=np.int64
    )
    starts = np.searchsorted(part_ids, np.arange(n_parts))
    slot_all = (
        np.arange(part_ids.size) - np.repeat(starts, counts)
        if part_ids.size
        else np.empty(0, np.int64)
    )

    groups = []
    max_b = 0
    for b in sorted(set(widths.tolist())):
        sel_parts = np.flatnonzero(widths == b)
        p_pad = max(1, math.ceil(len(sel_parts) / pad_parts_to) * pad_parts_to)
        buf = np.zeros((p_pad, b, d), dtype=dtype)
        mask = np.zeros((p_pad, b), dtype=bool)
        idx = np.full((p_pad, b), -1, dtype=np.int64)
        pid = np.full(p_pad, -1, dtype=np.int64)
        pid[: len(sel_parts)] = sel_parts
        if part_ids.size:
            row_of_part = np.full(n_parts, -1, dtype=np.int64)
            row_of_part[sel_parts] = np.arange(len(sel_parts))
            in_group = row_of_part[part_ids] >= 0
            gi = np.flatnonzero(in_group)
            rows = row_of_part[part_ids[gi]]
            slots = slot_all[gi]
            buf[rows, slots] = pts[point_idx[gi]].astype(dtype)
            mask[rows, slots] = True
            idx[rows, slots] = point_idx[gi]
        groups.append(BucketGroup(buf, mask, idx, pid))
        max_b = max(max_b, b)
    return groups, max_b


# Cell size safety factor over eps: a pair the device's f32 distance test
# could accept (true distance <= eps * (1 + few ulps)) must lie within the
# 3x3 cell ring, so cells are built marginally larger than eps. 1e-5 covers
# f32's ~1e-7/op rounding with orders of magnitude to spare, while growing
# windows imperceptibly.
CELL_SLACK = 1.0 + 1e-5

# Partitions narrower than this always use the dense engine: at small B the
# [B, B] sweep is already cheap and window bookkeeping is pure overhead.
MIN_BANDED_BUCKET = 4096

# At or above this width the dense engine is no longer an option at all — a
# [B, B] f32 measure matrix at B = 65536 is 17 GB, past a v5e chip's HBM —
# so auto ALWAYS routes such partitions through the banded engine. Below
# it, measured crossover on v5e: the dense sweep's perfectly-tiled [B, B]
# broadcasts beat the banded slab machinery unless the slabs shrink the
# work by a margin larger than their per-block overheads (~an order of
# magnitude).
DENSE_MAX_BUCKET = 65536

# Rows per block-slab tile in the banded engine; banded bucket widths are
# padded to a multiple of this. Bigger blocks amortize the per-slab DMA
# latency over more rows but widen the union slab S (waste ~6 cells'
# occupancy); 1024 measured fastest on v5e at bench densities. Lives here
# (host side) so the packer has no jax dependency; dbscan_tpu/ops/banded.py
# imports it.
BANDED_BLOCK = 1024


def bucketize_banded(
    points: np.ndarray,
    part_ids: np.ndarray,
    point_idx: np.ndarray,
    n_parts: int,
    eps: float,
    outer: np.ndarray,
    bucket_multiple: int = 128,
    pad_parts_to: int = 1,
    dtype=np.float32,
    force: bool = False,
) -> Tuple[list, int]:
    """Pack partitions for the banded engine (dbscan_tpu/ops/banded.py).

    Per partition: snap instances to an eps-sized grid anchored at the
    partition's outer rect, sort by cell row-major (stable, so equal-cell
    points keep fold order), and precompute each point's three contiguous
    candidate runs — one per neighboring cell row — in the sorted order.
    Runs are then grouped by blocks of BANDED_BLOCK consecutive rows: the
    per-(block, cell row) union of runs is the contiguous SLAB the device
    fetches with one dynamic_slice; the static slab bound S is the padded
    max slab length. Partitions where 3*S gives no real saving over the
    dense [B, B] sweep (or below MIN_BANDED_BUCKET, unless ``force``) fall
    back to dense groups.

    Groups by (width, S) for banded parts and width for dense parts; returns
    (groups, max width) like :func:`bucketize_grouped`, with ``banded`` set
    on the banded groups.
    """
    pts = np.asarray(points)
    if pts.shape[1] != 2:
        raise ValueError(f"banded bucketing is 2-D only, got D={pts.shape[1]}")
    m_tot = part_ids.size
    counts = np.bincount(part_ids, minlength=n_parts)
    part_start = np.concatenate([[0], np.cumsum(counts)])[:-1]
    widths_b = np.array(
        [_ladder_width(c, bucket_multiple) for c in counts], dtype=np.int64
    )

    if m_tot == 0:
        return bucketize_grouped(
            points, part_ids, point_idx, n_parts, bucket_multiple,
            pad_parts_to, dtype,
        )

    cell = float(eps) * CELL_SLACK
    xy = np.asarray(pts, dtype=np.float64)[point_idx]
    # Cells must be computed from the coordinates the DEVICE sees: under
    # f32/bf16 the cast can move a point across a float64 cell boundary
    # (quantization error scales with |coordinate|, far beyond CELL_SLACK's
    # arithmetic-rounding margin), and a run built from the float64 cell
    # would miss pairs the device's distance test accepts.
    xy_dev = xy.astype(dtype).astype(np.float64)
    ox = outer[part_ids, 0]
    oy = outer[part_ids, 1]
    cx = np.maximum(np.floor((xy_dev[:, 0] - ox) / cell), 0.0).astype(np.int64)
    cy = np.maximum(np.floor((xy_dev[:, 1] - oy) / cell), 0.0).astype(np.int64)

    # Segment maxima via reduceat (instances are sorted by partition);
    # ufunc.at is a scalar Python-level loop — ~10s at 5M instances.
    nz = counts > 0
    segs = part_start[nz]
    cxmax = np.zeros(n_parts, dtype=np.int64)
    cymax = np.zeros(n_parts, dtype=np.int64)
    if segs.size:
        cxmax[nz] = np.maximum.reduceat(cx, segs)
        cymax[nz] = np.maximum.reduceat(cy, segs)
    stride = cxmax + 3  # cx + 2 < stride: row windows never wrap
    key = cy * stride[part_ids] + cx
    big = int((stride * (cymax + 2)).max()) + 1  # per-partition key space

    # Stable sort by (partition, cell key): instances arrive in (partition,
    # fold) order, so ties keep fold order inside each cell.
    fold = np.arange(m_tot, dtype=np.int64) - part_start[part_ids]
    order = np.lexsort((key, part_ids))
    p_s = part_ids[order]
    gkey_s = p_s * big + key[order]
    cx_s, cy_s = cx[order], cy[order]
    fold_s = fold[order]
    ptidx_s = point_idx[order]
    xy_s = xy[order]
    slots_s = np.arange(m_tot, dtype=np.int64) - part_start[p_s]
    stride_s = stride[p_s]
    base_s = p_s * big
    seg_start = part_start[p_s]

    starts3 = np.empty((m_tot, 3), dtype=np.int64)
    spans3 = np.empty((m_tot, 3), dtype=np.int64)
    for k, dr in enumerate((-1, 0, 1)):
        row = cy_s + dr
        lo = base_s + row * stride_s + cx_s - 1
        s = np.searchsorted(gkey_s, lo)
        e = np.searchsorted(gkey_s, lo + 3)
        # lo can undershoot the partition's key space (cx=0 or row=-1);
        # clamp into this partition's segment so a neighboring partition's
        # tail never leaks into the window.
        s = np.maximum(s, seg_start)
        e = np.maximum(e, s)
        valid = (row >= 0) & (row <= cymax[p_s])
        starts3[:, k] = np.where(valid, s - seg_start, 0)
        spans3[:, k] = np.where(valid, e - s, 0)

    # Banded bucket widths: the dense ladder width padded up to a multiple
    # of the block size.
    t = BANDED_BLOCK
    widths_band = (widths_b + t - 1) // t * t
    nb_of = widths_band // t  # blocks per partition
    maxnb = int(nb_of.max())

    # Per-(partition block, cell row) slab = union of the block rows' runs:
    # min start / max end over valid runs.
    blk_s = slots_s // t
    bkey = p_s * maxnb + blk_s  # nondecreasing: p_s sorted, slots ascending
    n_bkeys = n_parts * maxnb
    bmin = np.zeros((n_bkeys, 3), dtype=np.int64)
    bmax = np.zeros((n_bkeys, 3), dtype=np.int64)
    run_valid = spans3 > 0
    for k in range(3):
        v = run_valid[:, k]
        bk = bkey[v]
        if bk.size == 0:
            continue
        st = starts3[v, k]
        first = np.flatnonzero(np.r_[True, bk[1:] != bk[:-1]])
        u = bk[first]
        bmin[u, k] = np.minimum.reduceat(st, first)
        bmax[u, k] = np.maximum.reduceat(st + spans3[v, k], first)

    slab_need = (bmax - bmin).max(axis=1).reshape(n_parts, maxnb).max(axis=1)
    win = np.minimum(
        np.array([_ladder_width(s, 128) for s in slab_need], dtype=np.int64),
        widths_band,  # slab can never exceed the bucket; ladder may overshoot
    )

    # Clamp slab origins so slab_start + S <= B; runs still fit (a clamped
    # origin only moves left, and run ends are bounded by the bucket width).
    part_of_bkey = np.repeat(np.arange(n_parts), maxnb)
    sstart = np.clip(bmin, 0, (widths_band - win)[part_of_bkey][:, None])

    if force:
        use_banded = counts > 0
    else:
        use_banded = (
            (counts > 0)
            & (widths_band >= MIN_BANDED_BUCKET)
            & (
                (widths_band >= DENSE_MAX_BUCKET)  # dense cannot fit HBM
                | (3 * win <= widths_band // 16)  # >=16x less sweep work
            )
        )

    groups: list = []
    max_b = 0

    # Dense fallback partitions go through the plain packer. Instances of
    # banded partitions are filtered out but n_parts keeps original ids;
    # the resulting zero-count rows land in the smallest-width group with
    # all-False masks and are skipped by the driver's instance scan.
    if not use_banded.all():
        dense_inst = ~use_banded[part_ids]
        if dense_inst.any() or not use_banded.any():
            dgroups, dmax = bucketize_grouped(
                points,
                part_ids[dense_inst],
                point_idx[dense_inst],
                n_parts,
                bucket_multiple,
                pad_parts_to,
                dtype,
            )
            groups.extend(dgroups)
            max_b = max(max_b, dmax)

    banded_inst = use_banded[p_s]
    # Per-instance run start within its slab; invalid runs (span 0) pin to 0
    # rather than inheriting a meaningless negative offset.
    rel3 = np.where(run_valid, starts3 - sstart[bkey], 0)
    for b, w in sorted(
        set(zip(widths_band[use_banded].tolist(), win[use_banded].tolist()))
    ):
        sel_parts = np.flatnonzero(
            use_banded & (widths_band == b) & (win == w)
        )
        nb = b // t
        p_pad = max(1, math.ceil(len(sel_parts) / pad_parts_to) * pad_parts_to)
        buf = np.zeros((p_pad, b, 2), dtype=dtype)
        mask = np.zeros((p_pad, b), dtype=bool)
        idx = np.full((p_pad, b), -1, dtype=np.int64)
        pid = np.full(p_pad, -1, dtype=np.int64)
        pid[: len(sel_parts)] = sel_parts
        iota = np.arange(b, dtype=np.int32)
        fold_b = np.broadcast_to(iota, (p_pad, b)).copy()
        pos_b = np.broadcast_to(iota, (p_pad, b)).copy()
        st_b = np.zeros((p_pad, b, 3), dtype=np.int32)
        sp_b = np.zeros((p_pad, b, 3), dtype=np.int32)
        sl_b = np.zeros((p_pad, nb, 3), dtype=np.int32)

        row_of_part = np.full(n_parts, -1, dtype=np.int64)
        row_of_part[sel_parts] = np.arange(len(sel_parts))
        gi = np.flatnonzero(banded_inst & (row_of_part[p_s] >= 0))
        rows = row_of_part[p_s[gi]]
        slots = slots_s[gi]
        buf[rows, slots] = xy_s[gi].astype(dtype)
        mask[rows, slots] = True
        idx[rows, slots] = ptidx_s[gi]
        fold_b[rows, slots] = fold_s[gi]
        pos_b[rows, fold_s[gi]] = slots
        st_b[rows, slots] = rel3[gi]
        sp_b[rows, slots] = spans3[gi]
        sl_b[: len(sel_parts)] = sstart[
            sel_parts[:, None] * maxnb + np.arange(nb)[None, :]
        ]

        groups.append(
            BucketGroup(
                buf, mask, idx, pid,
                BandedExtras(fold_b, pos_b, st_b, sp_b, sl_b, int(w)),
            )
        )
        max_b = max(max_b, b)
    return groups, max_b
