"""Host-side orchestration: spatial partitioning, halo binning, mesh fan-out,
and the global cluster merge."""
