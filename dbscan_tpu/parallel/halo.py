"""Collective halo-merge: the cross-partition cluster union as an
in-mesh fixed point.

The reference paper's global step is driver work: every executor ships
its doubly-labeled border points back, and the driver folds them through
a union-find (DBSCAN.scala:187-222). Our ``finalize_merge`` kept that
shape — ``graph.uf_components`` on the host — which means the one phase
that grows with the MESH (more chips = more borders) ran on one CPU.
arXiv:1912.06255's observation is that this merge is itself a connected-
components problem over a tiny graph and parallelizes cleanly once the
border unions become collectives; this module is that step as ONE
``shard_map`` kernel over the device mesh.

Shape of the computation:

- **Nodes** are the per-partition clusters of the merge step — dense
  RANKS into the unique ``(partition, local-id)`` table the driver
  builds (``_local_ids_flat``). Rank order is partition-major, so a
  contiguous block of ranks is a contiguous block of eps-halo'd spatial
  partitions: chip blocks on the mesh ARE the paper's executor blocks.
- **Edges** are the border unions: two clusters observed on the same
  eps-halo point (the doubly-labeled border seeds). The edge table
  shards over every mesh axis in contiguous blocks
  (``mesh.parts_spec``); the node label vector is replicated.
- **Iteration**: each round scatter-mins every shard's local edge
  contributions into its label copy, then reconciles the shards with a
  psum-style allreduce-min built from ``lax.ppermute`` neighbor
  exchanges — one ring per mesh axis, dimension-ordered, so on a real
  2-D slice each exchange only crosses torus neighbors — followed by
  one pointer jump (the classic compression step,
  ops/propagation.py). The ``lax.while_loop`` runs to the exact fixed
  point the host union-find computes: every node's label is its
  component-minimum rank.

Byte-identical numbering: ``graph.uf_components`` assigns dense 1-based
gids in first-appearance node order. A component's first appearance
scanning ranks 0..n-1 is exactly its minimum-rank member — the fixed
point's label value — so ``gid = cumsum(label == arange)[label]``
reproduces the host numbering bit-for-bit (pinned by
tests/test_meshshard.py against ``uf_components`` on random graphs and
end-to-end on every engine).

Shapes ride the usual ladders: nodes and edges pad to
``binning._ladder_width`` rungs rounded up to a mesh-size multiple
(``shard-indivisible``), so a second same-shaped sharded run compiles
ZERO new kernels. ``DBSCAN_MESH_MERGE=0`` keeps the host union-find as
the parity oracle; runs without a mesh (or a 1-device mesh) never enter
this path.

The sharded embed engine (embed/engine.py, ``DBSCAN_EMBED_SHARD``)
rides this kernel unchanged: its LSH boundary-spill duplicates ARE the
eps-halo points — a point spilled into two buckets is observed by both
owning chips, exactly like a doubly-labeled border seed — so the
cross-chip component union needs no embed-specific merge algebra, just
these border unions over bucket-band shards.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

from dbscan_tpu import config as config_mod
from dbscan_tpu import obs
from dbscan_tpu.parallel import mesh as mesh_mod
from dbscan_tpu.parallel.binning import _ladder_width

#: pad node the sentinel edges point at (self-loops: a no-op under min)
_PAD_MULT = 128


def _pad_up(n: int, k: int) -> int:
    """Ladder rung >= n, rounded up to a multiple of k (the mesh-axis
    block divisibility the shard-indivisible rule pins)."""
    w = _ladder_width(max(1, n), _PAD_MULT)
    return ((w + k - 1) // k) * k


@functools.lru_cache(maxsize=64)
def _compiled_halo_merge(n_pad: int, mesh, prop_mode: str = "iterated"):
    """Jitted collective fixed-point kernel for one (node width, mesh,
    propagation mode) triple; cached like the driver's dispatch
    builders so ladder-recurring shapes never re-trace. ``prop_mode``
    keys the trace: the union-find variant (DBSCAN_PROP_UNIONFIND)
    runs the SAME scatter-min edge relaxation but compresses with
    ``propagation._UF_JUMPS`` aggressive pointer-doubling jumps per
    round instead of one — same fixed point (byte-identical gids), the
    gated ``halo.rounds`` count collapses."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec

    from dbscan_tpu.ops import propagation as prop_lib

    jumps = (
        prop_lib._UF_JUMPS
        if prop_mode == "unionfind"
        else prop_lib._COMPRESS_JUMPS
    )

    axes = mesh_mod.parts_axes(mesh)
    sizes = {a: mesh.shape[a] for a in axes}

    def ring_min(x):
        # psum-style allreduce-min from ppermute neighbor exchanges:
        # one ring per mesh axis in turn (dimension-ordered), each step
        # passing the running partial to the next chip on that axis's
        # ring — torus-neighbor traffic only, unlike a flat all_gather
        acc = x
        for ax in axes:
            k = sizes[ax]
            perm = [(i, (i + 1) % k) for i in range(k)]
            part = acc
            for _ in range(k - 1):
                part = lax.ppermute(part, ax, perm)
                acc = jnp.minimum(acc, part)
        return acc

    def block(ua, ub):
        # ua/ub: this shard's block of the border-union edge table
        # (int32 ranks; sentinel self-loops at the pad node). Labels
        # start as identity over the full padded node space — tiny
        # (cluster count, not instance count), so every shard carries a
        # full copy and only EDGES shard.
        none = jnp.int32(n_pad - 1)

        def body(state):
            lab, _, it = state
            upd = lab.at[jnp.minimum(ua, none)].min(lab[jnp.minimum(ub, none)])
            upd = upd.at[jnp.minimum(ub, none)].min(lab[jnp.minimum(ua, none)])
            new = ring_min(upd)
            # pointer jumps per round: one on the iterated path (the
            # ops/propagation.py point-graph rationale), aggressive
            # doubling on the union-find path — the halo node graph is
            # tiny (cluster count), so jump gathers are cheap relative
            # to the ring exchange each ELIMINATED round saves
            for _ in range(jumps):
                new = jnp.minimum(new, new[new])
            return new, jnp.any(new != lab), it + 1

        def cond(state):
            _, changed, it = state
            return changed & (it < n_pad)

        init = jnp.arange(n_pad, dtype=jnp.int32)
        # one unrolled step first: the while_loop carry must be
        # data-derived for shard_map's type discipline, and body is
        # idempotent at the fixed point (same device as propagation.py)
        state = body((init, jnp.bool_(True), jnp.int32(0)))
        lab, _, iters = lax.while_loop(cond, body, state)
        return lab, iters

    espec = mesh_mod.parts_spec(mesh)
    return jax.jit(
        mesh_mod.shard_map(
            block,
            mesh=mesh,
            in_specs=(espec, espec),
            out_specs=(PartitionSpec(), PartitionSpec()),
            # the carry mixes varying scatter results with the psum-style
            # ring reconciliation inside lax.while_loop; the vma checker
            # has no rule for that composition (values are replicated by
            # construction after every ring — pinned against the host
            # union-find by tests/test_meshshard.py)
            check_vma=False,
        )
    )


def collective_merge(
    ua: np.ndarray,
    ub: np.ndarray,
    n_uniq: int,
    mesh,
    shape_floors: Optional[dict] = None,
) -> Tuple[int, np.ndarray]:
    """In-mesh replacement for ``graph.uf_components`` over the border
    union edges: returns ``(n_clusters, gid_of_u [n_uniq] int64)``,
    byte-identical to the host union-find (module docstring).

    ``shape_floors``: the streaming ratchet dict (binning._ratchet) —
    padded widths only grow across micro-batches so steady-state
    updates reuse exact jit signatures.
    """
    from dbscan_tpu.obs import compile as obs_compile
    from dbscan_tpu.ops import propagation as prop_lib
    from dbscan_tpu.parallel.binning import _ratchet

    if n_uniq == 0:
        # nothing to merge: skip the dispatch AND the cross-host pulls
        # (collectives in multi-process runs) a sentinel-only fixed
        # point would burn
        return 0, np.empty(0, dtype=np.int64)
    k = mesh_mod.mesh_size(mesh)
    n_pad = _ratchet(
        shape_floors, "halo_nodes", _pad_up(n_uniq + 1, k)
    )
    e_pad = _ratchet(
        shape_floors, "halo_edges", _pad_up(max(1, len(ua)), k)
    )
    # sentinel self-loops at the pad node: scatter-min no-ops
    ua_p = np.full(e_pad, n_pad - 1, dtype=np.int32)
    ub_p = np.full(e_pad, n_pad - 1, dtype=np.int32)
    ua_p[: len(ua)] = ua
    ub_p[: len(ub)] = ub
    mode = prop_lib.prop_mode()
    fn = _compiled_halo_merge(n_pad, mesh, mode)
    lab_dev, iters_dev = obs_compile.tracked_call(
        "halo.merge",
        fn,
        mesh_mod.shard_host_array(mesh, ua_p),
        mesh_mod.shard_host_array(mesh, ub_p),
    )
    lab = mesh_mod.pull_to_host(lab_dev)[:n_uniq].astype(np.int64)
    rounds = int(mesh_mod.pull_to_host(iters_dev))
    obs.count("halo.rounds", rounds)
    prop_lib.note_sweeps(rounds, mode)
    obs.count("halo.edges", int(len(ua)))
    obs.count("halo.nodes", int(n_uniq))
    # dense 1-based gids in first-appearance order == component-min-rank
    # order (a component first appears at its min-rank member, which is
    # exactly the fixed-point label value)
    is_root = lab == np.arange(n_uniq, dtype=np.int64)
    gid_of_root = np.cumsum(is_root)
    return int(gid_of_root[-1]), gid_of_root[lab].astype(np.int64)


def merge_active(mesh) -> bool:
    """True when the collective halo-merge replaces the host union-find:
    a real (multi-device) mesh with ``DBSCAN_MESH_MERGE`` on."""
    return (
        mesh is not None
        and mesh_mod.mesh_size(mesh) > 1
        and bool(config_mod.env("DBSCAN_MESH_MERGE"))
    )
