"""Cluster-alias graph + union-find for the global merge.

Two implementations of the same capability (merging per-partition cluster ids
that were observed on the same halo point, reference DBSCANGraph.scala:24-89 +
DBSCAN.scala:187-222):

- :class:`DBSCANGraph` — API-parity immutable undirected graph with BFS
  transitive closure (``get_connected``), mirroring DBSCANGraph.scala
  (addVertex :42-47, insert_edge :52-57, connect :63-65, getConnected :70-87).
  Kept because the reference exposes it as a public component and its unit
  tests pin its surface (DBSCANGraphSuite.scala:22-64).
- :class:`UnionFind` — path-compressed weighted union-find; O(alpha(n)) merge
  used by the production driver path, where the reference's driver instead
  folds the graph + getConnected per cluster id (DBSCAN.scala:206-222,
  quadratic-ish). Same resulting global numbering when ids are offered in the
  same order.
"""

from __future__ import annotations

from typing import Dict, Generic, Hashable, List, Set, Tuple, TypeVar

T = TypeVar("T", bound=Hashable)


class DBSCANGraph(Generic[T]):
    """Immutable undirected graph over hashable vertices.

    Structure-parity port of reference DBSCANGraph.scala:24-89. Every mutation
    returns a new graph; the adjacency map is never shared mutably.
    """

    __slots__ = ("_nodes",)

    def __init__(self, nodes: Dict[T, frozenset] = None):
        self._nodes: Dict[T, frozenset] = dict(nodes) if nodes else {}

    def add_vertex(self, v: T) -> "DBSCANGraph[T]":
        """Add vertex with no edges if absent (DBSCANGraph.scala:42-47)."""
        if v in self._nodes:
            return self
        nodes = dict(self._nodes)
        nodes[v] = frozenset()
        return DBSCANGraph(nodes)

    def insert_edge(self, frm: T, to: T) -> "DBSCANGraph[T]":
        """Add directed edge frm->to (DBSCANGraph.scala:52-57)."""
        nodes = dict(self._nodes)
        nodes[frm] = self._nodes.get(frm, frozenset()) | {to}
        return DBSCANGraph(nodes)

    def connect(self, one: T, another: T) -> "DBSCANGraph[T]":
        """Add the undirected edge (DBSCANGraph.scala:63-65)."""
        return self.insert_edge(one, another).insert_edge(another, one)

    def get_connected(self, frm: T) -> Set[T]:
        """All vertices transitively reachable from `frm`, excluding `frm`
        itself (DBSCANGraph.scala:70-87). Unknown vertices yield the empty
        set."""
        to_visit = [frm]
        visited: Set[T] = set()
        adjacent: Set[T] = set()
        while to_visit:
            current = to_visit.pop()
            if current in visited:
                continue
            visited.add(current)
            edges = self._nodes.get(current)
            if edges is None:
                continue
            adjacent |= edges
            to_visit.extend(e for e in edges if e not in visited)
        return adjacent - {frm}

    @property
    def vertices(self) -> Set[T]:
        return set(self._nodes)


def uf_components(edge_a, edge_b, n: int):
    """Connected components over integer-rank edges: (n_comp, gid [n]
    int64 1-based dense ids in first-appearance node order). Native
    (hostops.cpp::uf_assign_gids) with the dict UnionFind fallback —
    the one shape shared by the merge driver and the sparse prefix
    pre-split."""
    import numpy as np

    from dbscan_tpu import _native

    res = _native.uf_assign_gids(edge_a, edge_b, n)
    if res is not None:
        return res
    uf = UnionFind()
    for a, b in zip(edge_a, edge_b):
        uf.union(int(a), int(b))
    n_comp, mapping = uf.assign_global_ids(list(range(n)))
    gids = np.fromiter(
        (mapping[i] for i in range(n)), dtype=np.int64, count=n
    )
    return n_comp, gids


class UnionFind(Generic[T]):
    """Weighted quick-union with path compression over hashable keys.

    Production replacement for the reference's fold-over-getConnected global
    id assignment (DBSCAN.scala:206-222). ``assign_global_ids`` reproduces the
    reference's numbering contract: iterate cluster ids in a caller-fixed
    order, give each not-yet-seen connected component the next integer id
    starting from 1 (0 stays UNKNOWN/noise).
    """

    def __init__(self):
        self._parent: Dict[T, T] = {}
        self._size: Dict[T, int] = {}

    def find(self, x: T) -> T:
        parent = self._parent
        if x not in parent:
            parent[x] = x
            self._size[x] = 1
            return x
        root = x
        while parent[root] != root:
            root = parent[root]
        while parent[x] != root:
            parent[x], x = root, parent[x]
        return root

    def union(self, a: T, b: T) -> T:
        ra, rb = self.find(a), self.find(b)
        if ra == rb:
            return ra
        if self._size[ra] < self._size[rb]:
            ra, rb = rb, ra
        self._parent[rb] = ra
        self._size[ra] += self._size[rb]
        return ra

    def assign_global_ids(self, ordered_keys: List[T]) -> Tuple[int, Dict[T, int]]:
        """Map each key to a global cluster id; connected keys share one id.

        Mirrors DBSCAN.scala:206-222: ids are dense, 1-based, assigned in
        first-appearance order of `ordered_keys`' components. Returns
        (total_unique, mapping).
        """
        mapping: Dict[T, int] = {}
        root_to_id: Dict[T, int] = {}
        next_id = 0
        for key in ordered_keys:
            root = self.find(key)
            gid = root_to_id.get(root)
            if gid is None:
                next_id += 1
                gid = next_id
                root_to_id[root] = gid
            mapping[key] = gid
        return next_id, mapping
