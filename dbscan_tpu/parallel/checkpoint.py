"""Pre-merge checkpoint: resumable 100M+ runs.

The expensive phases of a distributed run — decomposition, halo
duplication, packing, and the per-partition device clustering — all
complete BEFORE the host merge, and their entire output is a set of flat
instance tables (partition id, point row, seed label, flag, merge
classification) plus the partition rectangles. This module serializes
exactly that state, so a run killed any time after the device phase
resumes straight at ``finalize_merge`` instead of re-clustering.

The reference has no checkpoint story of its own — it leans on Spark
lineage to recompute lost partitions (DBSCAN.scala:59-60 persists the
duplicated RDD). Lineage replays the SAME expensive work on failure;
this checkpoint makes the replay a file read.

Format: ``premerge.npz`` (atomic rename) + ``manifest.json`` holding the
run fingerprint and scalar metadata. The fingerprint covers the input
shape/dtype, strided data samples (hashing 100M+ rows in full would cost
more than the merge it saves), and every config field that changes the
instance tables; a mismatch silently ignores the checkpoint and the run
recomputes from scratch.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import logging
import os
import time
import zipfile
from typing import Optional

import numpy as np

try:  # POSIX file locks guard the progress sidecar's read-modify-write
    import fcntl
except ImportError:  # pragma: no cover - non-posix fallback (no locking)
    fcntl = None

from dbscan_tpu import config, obs

logger = logging.getLogger(__name__)

_FORMAT_VERSION = 1
_NPZ = "premerge.npz"
_MANIFEST = "manifest.json"


def run_fingerprint(pts: np.ndarray, cfg) -> str:
    """Digest of the inputs that determine the pre-merge state.

    Data is sampled (first/last 4096 rows + a ~4096-row stride through the
    middle), not hashed in full: at north-star scale a full pass costs
    seconds of pure overhead per run for collision resistance this use
    (same-machine resume, not content addressing) does not need.
    """
    h = hashlib.sha256()
    h.update(f"v{_FORMAT_VERSION}|{pts.shape}|{pts.dtype}|".encode())
    head = np.ascontiguousarray(pts[:4096])
    tail = np.ascontiguousarray(pts[-4096:])
    step = max(1, len(pts) // 4096)
    mid = np.ascontiguousarray(pts[::step])
    for part in (head, tail, mid):
        h.update(part.tobytes())
    h.update(
        json.dumps(
            {
                "eps": cfg.eps,
                "min_points": cfg.min_points,
                "max_points_per_partition": cfg.max_points_per_partition,
                "metric": cfg.metric,
                "engine": cfg.engine.value,
                "precision": cfg.precision.value,
                "neighbor_backend": cfg.neighbor_backend,
                "bucket_multiple": cfg.bucket_multiple,
                "use_pallas": cfg.use_pallas,
                # changes the bound handed to the partitioner, hence the
                # whole layout the saved state encodes
                "auto_maxpp": getattr(cfg, "auto_maxpp", False),
                # both change group batching/padding, hence the p1-chunk
                # composition the ordinal-salted chunk signatures
                # describe; shapes are ladder-quantized so sigs alone can
                # collide across layouts — key the whole checkpoint space
                # on them instead. group_slots is NORMALIZED to the int
                # binning actually uses so equivalent spellings (unset vs
                # the explicit default) keep their checkpoints.
                "static_partition_pad": getattr(
                    cfg, "static_partition_pad", False
                ),
                "group_slots": int(config.env("DBSCAN_GROUP_SLOTS")),
            },
            sort_keys=True,
        ).encode()
    )
    return h.hexdigest()


def save_premerge(
    ckpt_dir: str,
    fingerprint: str,
    arrays: dict,
    scalars: dict,
) -> None:
    """Write the pre-merge state atomically (tmp + rename): a reader never
    sees a torn checkpoint, and a crash mid-write leaves the previous
    checkpoint (if any) intact. The fingerprint is ALSO embedded in the
    npz: rename is atomic per file, not across the npz/manifest pair, so
    a crash between the two replaces could otherwise pair one run's
    arrays with another run's manifest — the loader cross-checks."""
    t0 = time.perf_counter()
    os.makedirs(ckpt_dir, exist_ok=True)
    npz_tmp = os.path.join(ckpt_dir, _NPZ + ".tmp")
    with open(npz_tmp, "wb") as f:
        np.savez(f, _fingerprint=np.array(fingerprint), **arrays)
    os.replace(npz_tmp, os.path.join(ckpt_dir, _NPZ))
    obs.count(
        "checkpoint.premerge_bytes",
        int(sum(a.nbytes for a in arrays.values())),
    )
    obs.add_span("checkpoint.save_premerge", t0, time.perf_counter())
    man_tmp = os.path.join(ckpt_dir, _MANIFEST + ".tmp")
    with open(man_tmp, "w") as f:
        json.dump(
            {
                "format_version": _FORMAT_VERSION,
                "fingerprint": fingerprint,
                "scalars": scalars,
            },
            f,
        )
    os.replace(man_tmp, os.path.join(ckpt_dir, _MANIFEST))


def load_premerge(ckpt_dir: str, fingerprint: str) -> Optional[dict]:
    """Load a checkpoint matching ``fingerprint``; None when absent, torn,
    stale-format, or written for different data/config (resume must never
    be less safe than recomputing)."""
    man_path = os.path.join(ckpt_dir, _MANIFEST)
    npz_path = os.path.join(ckpt_dir, _NPZ)
    if not (os.path.exists(man_path) and os.path.exists(npz_path)):
        return None
    try:
        with open(man_path) as f:
            man = json.load(f)
        if man.get("format_version") != _FORMAT_VERSION:
            return None
        if man.get("fingerprint") != fingerprint:
            return None
        with np.load(npz_path) as z:
            if str(z["_fingerprint"]) != fingerprint:
                return None  # npz and manifest from different runs
            arrays = {k: z[k] for k in z.files if k != "_fingerprint"}
    except (
        OSError,
        ValueError,
        KeyError,
        json.JSONDecodeError,
        zipfile.BadZipFile,  # truncated npz with intact zip magic
    ):
        return None
    return {"arrays": arrays, "scalars": man["scalars"]}


# --- phase-1 chunk checkpoints (resumable device phase) ---------------
#
# The tunneled TPU worker can die mid-run (observed: consistently after
# ~15-25 min of continuous device work at 100M points), and the premerge
# checkpoint above only exists once EVERY group's device work finished.
# These per-chunk artifacts close that gap: the driver's eager compact
# path saves each chunk's pulled postpass output (packed core bits +
# or-values + border bitmasks — a few dozen MB per ~2^28-slot chunk) as
# it lands, and a resumed run re-packs (deterministic), skips device
# dispatch for groups covered by saved chunks, and recomputes only the
# groups after the last saved chunk. This is the elastic-recovery story
# the reference delegates wholesale to Spark lineage (DBSCAN.scala:59-60)
# — except a replay here is a file read, not a recompute.

_P1_PREFIX = "p1chunk"


def _p1_path(ckpt_dir: str, ci: int) -> str:
    return os.path.join(ckpt_dir, f"{_P1_PREFIX}{ci:04d}.npz")


def invalidate_p1_chunk(ckpt_dir: str, ci: int) -> None:
    """Remove a stale saved chunk (its composition diverged from the
    current emission plan) AND every saved chunk above it, so future
    legs' consecutive-prefix load truncates cleanly at ``ci``: the
    loader only consumes a consecutive prefix, so higher-index files
    left behind the gap are unreachable — and if a later leg's saves
    ever filled the gap, the stale survivors would load as placeholders
    whose signatures cannot match, cascading divergences. Note the
    CURRENT leg's post-divergence saves land at indices above the old
    placeholder count (chunk ids count all records), so they too sit
    behind the gap and are lost to the next leg; the numbering heals
    only on the next leg, which restarts at the truncation point.
    Divergence is the rare path (a changed plan slipping past the
    fingerprint — never a fixed-settings retry loop), so that one-leg
    recompute is accepted over renumbering saved files, whose order
    must stay aligned with the canonical ordinal prefix."""
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return
    for name in names:
        if not (name.startswith(_P1_PREFIX) and name.endswith(".npz")):
            continue
        try:
            idx = int(name[len(_P1_PREFIX) : -len(".npz")])
        except ValueError:
            continue
        if idx >= ci:
            try:
                os.unlink(os.path.join(ckpt_dir, name))
            except OSError:
                pass


def save_p1_chunk(
    ckpt_dir: str,
    fingerprint: str,
    ci: int,
    sig: str,
    shapes: np.ndarray,
    arrays: dict,
    budget: int = 0,
) -> None:
    """Atomically persist one pulled compact chunk. ``sig`` digests the
    chunk's group composition; ``shapes`` is [n_groups, 3] int64
    (P, B, slab) — the loader exposes it so the resuming driver can skip
    matching group dispatches BEFORE the chunk re-forms. ``budget`` is
    the chunk-slot budget the chunks were formed under: the loader
    rejects chunks from a different budget OUTRIGHT (their compositions
    cannot re-form, and per-group skips followed by signature-mismatch
    redispatch would serialize the whole device phase)."""
    t0 = time.perf_counter()
    os.makedirs(ckpt_dir, exist_ok=True)
    path = _p1_path(ckpt_dir, ci)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(
            f,
            _fingerprint=np.array(fingerprint),
            _sig=np.array(sig),
            _shapes=shapes,
            _budget=np.int64(budget),
            **arrays,
        )
    os.replace(tmp, path)
    # monotone write counter in the progress sidecar: the leg-progress
    # signal retry harnesses read (bench.py / campaign.py) instead of
    # trusting file mtimes. Best-effort — a failed bump must never turn
    # a successfully banked chunk into a failed save (the mtime
    # fallback still sees the file).
    try:
        bump_progress(ckpt_dir, PROGRESS_WRITE_COUNTER)
    except Exception:  # noqa: BLE001 — pragma: no cover
        # ANY sidecar failure (fs error, foreign/corrupt progress.json)
        # must not turn the successfully banked chunk into a failed
        # save; the mtime fallback still sees the file
        pass
    obs.count("checkpoint.chunks_saved")
    obs.count(
        "checkpoint.chunk_bytes",
        int(sum(a.nbytes for a in arrays.values())),
    )
    obs.add_span(
        "checkpoint.save_p1_chunk", t0, time.perf_counter(), chunk=int(ci)
    )


def load_p1_chunks(
    ckpt_dir: str, fingerprint: str, budget: int = 0
) -> list:
    """Load the consecutive prefix of saved chunks matching
    ``fingerprint`` AND ``budget`` (chunk ci is only usable if every
    chunk before it loaded — the driver skips dispatches in emission
    order). Returns a list of dicts {sig, shapes, arrays}; empty on any
    mismatch."""
    out = []
    ci = 0
    while True:
        path = _p1_path(ckpt_dir, ci)
        if not os.path.exists(path):
            break
        try:
            with np.load(path) as z:
                if str(z["_fingerprint"]) != fingerprint:
                    break
                if int(z["_budget"]) != int(budget):
                    break
                out.append(
                    {
                        "sig": str(z["_sig"]),
                        "shapes": z["_shapes"],
                        "arrays": {
                            k: z[k]
                            for k in z.files
                            if not k.startswith("_")
                        },
                    }
                )
        except (OSError, ValueError, KeyError, zipfile.BadZipFile):
            break
        ci += 1
    if out:
        obs.count("checkpoint.chunks_loaded", len(out))
    return out


def p1_chunk_indices(
    ckpt_dir: str, fingerprint: str, budget: int = 0
) -> list:
    """ALL saved chunk indices matching ``fingerprint`` and ``budget``,
    gaps allowed — campaign legs (dbscan_tpu/campaign.py) bank disjoint
    chunk subsets out of order, and the lease queue needs to know which
    indices are already on disk so a resumed campaign only leases the
    holes. The consecutive-prefix :func:`load_p1_chunks` stays the
    merge-time gate: a finalize run adopts chunks only once the prefix
    is complete."""
    out = []
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return out
    for name in names:
        if not (name.startswith(_P1_PREFIX) and name.endswith(".npz")):
            continue
        try:
            ci = int(name[len(_P1_PREFIX) : -len(".npz")])
        except ValueError:
            continue
        try:
            with np.load(os.path.join(ckpt_dir, name)) as z:
                if str(z["_fingerprint"]) != fingerprint:
                    continue
                if int(z["_budget"]) != int(budget):
                    continue
        except (OSError, ValueError, KeyError, zipfile.BadZipFile):
            continue  # torn file: the hole gets re-leased
        out.append(ci)
    return sorted(out)


# --- serve state checkpoints (resident ClusterService) -----------------
#
# The serving layer (dbscan_tpu/serve) is long-lived by design, and the
# signal it dies to — SIGTERM preemption — arrives mid-ingest. Its
# checkpoint is tiny compared to the premerge state above: the stream's
# window skeleton + identity union-find (streaming.export_state), a few
# MB even at production window sizes. Same torn-write discipline as the
# premerge pair: atomic npz with the fingerprint embedded, loader
# rejects mismatches outright (a resumed server must never adopt
# another stream's identity state — relabeling drift is the one failure
# the serving contract forbids).

_SERVE_NPZ = "serve_state.npz"


def _serve_path(ckpt_dir: str, shard: Optional[int]) -> str:
    """The per-shard serve checkpoint path: the obs.flush() shard-suffix
    convention (``<path>.<shard>``) so N ingest shards of one sharded
    service can never clobber each other's snapshot; an unsharded
    service (shard None) keeps the historical unsuffixed name."""
    base = os.path.join(ckpt_dir, _SERVE_NPZ)
    return base if shard is None else f"{base}.{int(shard)}"


def save_serve(
    ckpt_dir: str,
    fingerprint: str,
    arrays: dict,
    scalars: dict,
    quiet: bool = False,
    shard: Optional[int] = None,
    n_shards: int = 1,
) -> str:
    """Atomically persist one serve/stream state snapshot; returns the
    written path. Signal-handler safe by construction with ``quiet``
    set: one tmp write + rename, no locks taken — the telemetry hooks
    (which DO take the registry locks) are skipped, because the
    SIGTERM-interrupted frame may already hold them. The arrays are an
    immutable published snapshot, never live mutable state.

    ``shard``/``n_shards``: sharded services write one suffixed file
    per ingest shard (:func:`_serve_path`) with the shard layout
    embedded next to the stream fingerprint, so a resume under a
    DIFFERENT shard count refuses instead of silently adopting a
    partition's identity state as the whole stream's."""
    t0 = time.perf_counter()
    os.makedirs(ckpt_dir, exist_ok=True)
    path = _serve_path(ckpt_dir, shard)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(
            f,
            _fingerprint=np.array(fingerprint),
            _scalars=np.array(json.dumps(scalars)),
            _shards=np.array(
                [int(shard) if shard is not None else 0, int(n_shards)]
            ),
            **arrays,
        )
    os.replace(tmp, path)
    if not quiet:
        obs.count("checkpoint.serve_saves")
        obs.count(
            "checkpoint.serve_bytes",
            int(sum(a.nbytes for a in arrays.values())),
        )
        obs.add_span("checkpoint.save_serve", t0, time.perf_counter())
    return path


def load_serve(
    ckpt_dir: str,
    fingerprint: str,
    shard: Optional[int] = None,
    n_shards: int = 1,
) -> Optional[dict]:
    """Load a serve state matching ``fingerprint``; None when absent,
    torn, or written for a different stream config (resume must never
    be less safe than starting a fresh stream). A shard-count mismatch
    — the file was written by a service sharded differently than the
    caller — REFUSES with a warning rather than part-loading: adopting
    one layout's partition state under another layout would relabel,
    the one failure the serving contract forbids. (Files written before
    the shard fingerprint existed carry no ``_shards`` entry and only
    load unsharded, the layout they were written under.)"""
    path = _serve_path(ckpt_dir, shard)
    if not os.path.exists(path):
        return None
    want_shard = int(shard) if shard is not None else 0
    try:
        with np.load(path) as z:
            if str(z["_fingerprint"]) != fingerprint:
                return None
            if "_shards" in z.files:
                got_shard, got_n = (int(v) for v in z["_shards"])
            else:
                got_shard, got_n = 0, 1
            if got_shard != want_shard or got_n != int(n_shards):
                logger.warning(
                    "serve checkpoint %s was written as shard %d of %d "
                    "but this service is shard %d of %d — refusing the "
                    "restore (starting fresh identity state)",
                    path, got_shard, got_n, want_shard, int(n_shards),
                )
                return None
            scalars = json.loads(str(z["_scalars"]))
            arrays = {
                k: z[k] for k in z.files if not k.startswith("_")
            }
    except (
        OSError,
        ValueError,
        KeyError,
        json.JSONDecodeError,
        zipfile.BadZipFile,
    ):
        return None
    obs.count("checkpoint.serve_loads")
    return {"arrays": arrays, "scalars": scalars}


# --- campaign progress sidecar ----------------------------------------
#
# A retry-resume harness (bench.py::m100_row) needs two numbers a dead
# leg cannot report: how many restart points exist on disk, and how many
# the full run will need. The driver writes the plan-derived total here
# the moment binning's canonical emission plan is known (minutes before
# the first chunk could land); chunks_done is just the consecutive file
# prefix — files behind a gap never resume (see load_p1_chunks).

_PROGRESS = "progress.json"
_PROGRESS_LOCK = _PROGRESS + ".lock"

#: monotonic count of p1-chunk WRITES in this checkpoint dir, bumped by
#: :func:`save_p1_chunk` under the progress lock. Distinct from the
#: consecutive-prefix ``chunks_done`` figure: a resumed leg overwriting
#: chunk indices in place still bumps it, so a retry harness reads a
#: counter DELTA as "this leg banked something" without trusting
#: filesystem mtimes (coarse granularity / clock skew can misclassify a
#: productive leg as stalled — two misses kills a campaign).
PROGRESS_WRITE_COUNTER = "chunks_written"


@contextlib.contextmanager
def _progress_locked(ckpt_dir: str):
    """Exclusive advisory lock over the progress sidecar. flock locks
    are per open file description, so this serializes BOTH concurrent
    processes (campaign legs vs. the harness) and concurrent threads
    (each entry opens its own fd). Non-posix platforms degrade to no
    locking — same behavior as before this lock existed."""
    os.makedirs(ckpt_dir, exist_ok=True)
    if fcntl is None:  # pragma: no cover - non-posix
        yield
        return
    with open(os.path.join(ckpt_dir, _PROGRESS_LOCK), "a+") as f:
        fcntl.flock(f, fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(f, fcntl.LOCK_UN)


def _write_progress_locked(ckpt_dir: str, prog: dict) -> None:
    path = os.path.join(ckpt_dir, _PROGRESS)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(prog, f)
    os.replace(tmp, path)


def write_progress(ckpt_dir: str, **fields) -> None:
    """MERGE campaign-progress metadata into progress.json under the
    progress file lock (atomic replace; readers never see a torn file).

    Merge — not replace — because the sidecar has concurrent writers
    with disjoint keys: the driver's plan write (``chunks_total``),
    the abort path (``aborted_*``), the chunk-save counter bump, and N
    campaign workers' legs. An unlocked read-modify-write (or a
    replacing write) could silently drop another writer's fields —
    the lost-update race the concurrent-writer regression test pins.
    Keys persist until overwritten: readers treat ``aborted_*`` as
    "most recent abort", not "currently aborted"."""
    with _progress_locked(ckpt_dir):
        prog = read_progress(ckpt_dir)
        prog.update(fields)
        _write_progress_locked(ckpt_dir, prog)


def bump_progress(ckpt_dir: str, key: str, by: int = 1) -> int:
    """Atomically increment an integer progress field (missing = 0)
    under the progress lock; returns the new value. A corrupt
    (non-numeric) stored value restarts the counter from 0 rather than
    raising — the counter is a progress heuristic, and its failure
    must never poison the chunk save that triggered the bump."""
    with _progress_locked(ckpt_dir):
        prog = read_progress(ckpt_dir)
        try:
            val = int(prog.get(key, 0))
        except (TypeError, ValueError):
            val = 0
        val += int(by)
        prog[key] = val
        _write_progress_locked(ckpt_dir, prog)
    return val


def note_abort(ckpt_dir: str, **fields) -> None:
    """Merge abort metadata (the supervised-dispatch site/ordinal that
    exhausted its retries, dbscan_tpu/faults.py) into progress.json so a
    retry-resume harness can report WHERE a dead leg stopped — the
    driver's abort path flushes its compact chunk and records this just
    before the fatal fault propagates. Merge-under-lock: a concurrent
    plan write or counter bump can no longer drop these fields."""
    write_progress(ckpt_dir, **fields)


def read_progress(ckpt_dir: str) -> dict:
    try:
        with open(os.path.join(ckpt_dir, _PROGRESS)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def count_p1_chunks(ckpt_dir: str) -> int:
    """Length of the consecutive p1chunk file prefix — the number of
    restart points a resuming leg can actually consume (fingerprint and
    budget are verified at load time, not here)."""
    ci = 0
    while os.path.exists(_p1_path(ckpt_dir, ci)):
        ci += 1
    return ci
