"""Even-split spatial partitioner (host-side, exact integer arithmetic).

Recursive binary space partitioning of the 2eps cell histogram so every
rectangle holds at most ``max_points_per_partition`` points — the capability
of reference EvenSplitPartitioner.scala:26-211, with three TPU-era changes:

1. **Exact integer domain.** The reference partitions in accumulated doubles
   (cuts at ``x + k*minSize``, EvenSplitPartitioner.scala:148-162) while cell
   corners come from ``trunc(p/minSize)*minSize`` (DBSCAN.scala:352-356); the
   two drift apart by ulps, silently dropping cells from partition counts and
   — after the empty-partition filter — leaving coverage holes. We partition
   on integer cell indices (one unit == one ``minimum_rectangle_size`` cell),
   where every cut, complement, and containment test is exact. See
   tests/test_partitioner.py::test_no_points_lost_to_fp_drift.
2. Candidate-cut evaluation is O(cells + extent) per split: every cut count
   comes from one per-axis histogram + prefix sum over the cells of the rect
   being split, instead of re-scanning the cell set per candidate cut (the
   reference's hot spot, :105-123 + :175-181).
3. The candidate order is DETERMINISTIC: x-cuts ascending, then y-cuts
   ascending, first-win on cost ties. The reference iterates a hash Set
   (:148-162) yet its unit test pins exact output; this fixed order
   reproduces both EvenSplitPartitionerSuite fixtures exactly (verified in
   tests/test_partitioner.py), so it is the reference order made explicit.

Semantics preserved exactly (all cited to EvenSplitPartitioner.scala):
- cost(r) = |pointsIn(whole) / 2 - pointsIn(r)| with integer halving (:81);
- cuts at every interior multiple of the minimum rectangle size (:148-162);
- canBeSplit: strictly greater than 2 cells on either axis (:168-171);
- a too-big unsplittable rectangle is emitted as-is with a warning (:85-92);
- depth-first recursion, first half first (:87-88), results effectively
  prepended (:94-99) — final order is reverse completion order;
- zero-count partitions dropped at the end (:63);
- pointsIn counts cells FULLY contained in the rectangle (:175-181).
"""

from __future__ import annotations

import logging
from typing import List, Tuple

import numpy as np

logger = logging.getLogger(__name__)

# Integer rect layout: (x, y, x2, y2) in cell units, lower-left inclusive,
# upper-right exclusive-as-boundary (a rect spans cells [x, x2) x [y, y2)).
X, Y, X2, Y2 = 0, 1, 2, 3


def _points_in(cells: np.ndarray, counts: np.ndarray, rects: np.ndarray) -> np.ndarray:
    """Counts of points whose (unit) cells are fully inside each integer rect.

    cells: [C, 2] lower-left indices (each cell spans +1 unit);
    rects: [K, 4] -> [K] int64.
    (Reference pointsInRectangle, EvenSplitPartitioner.scala:175-181.)
    """
    rects = np.atleast_2d(rects)
    cx, cy = cells[:, 0], cells[:, 1]
    out = np.empty(rects.shape[0], dtype=np.int64)
    # Chunk the candidate axis: K can reach tens of thousands on wide fine
    # grids and a single [K, C] bool broadcast would be gigabytes.
    chunk = max(1, int(2**24 // max(1, cx.size)))
    for s in range(0, rects.shape[0], chunk):
        r = rects[s : s + chunk]
        inside = (
            (r[:, None, X] <= cx[None, :])
            & (cx[None, :] + 1 <= r[:, None, X2])
            & (r[:, None, Y] <= cy[None, :])
            & (cy[None, :] + 1 <= r[:, None, Y2])
        )  # [k, C]
        out[s : s + chunk] = inside @ counts
    return out


def _candidate_counts(
    rect: np.ndarray, cx: np.ndarray, cy: np.ndarray, w: np.ndarray
) -> np.ndarray:
    """Counts for every candidate sub-rectangle of `rect` in _possible_splits
    order (x-cuts ascending, then y-cuts ascending), in O(C + extent) via
    per-axis histograms + prefix sums instead of a [K, C] rescan.

    cx/cy/w are the cells inside `rect` and their point counts. The candidate
    at x-cut c spans [x, c) x [y, y2); a unit cell is fully inside iff
    cx + 1 <= c, so its count is the prefix sum of the column histogram up to
    c - x - 1 (all cells already satisfy the y bounds — they lie in rect).
    Exact integer arithmetic throughout.
    """
    x, y, x2, y2 = (int(v) for v in rect)
    bx = np.bincount(cx - x, weights=w, minlength=x2 - x).astype(np.int64)
    by = np.bincount(cy - y, weights=w, minlength=y2 - y).astype(np.int64)
    # cut c = x+1+j  ->  count = cumx[j], for j in [0, x2-x-2]
    return np.concatenate(
        [np.cumsum(bx)[: x2 - x - 1], np.cumsum(by)[: y2 - y - 1]]
    )


def _possible_splits(rect: np.ndarray) -> np.ndarray:
    """All candidate sub-rectangles sharing the bottom-left corner: x-cuts
    ascending then y-cuts ascending (EvenSplitPartitioner.scala:148-162),
    one candidate per interior integer cut."""
    x, y, x2, y2 = (int(v) for v in rect)
    xs = [[x, y, c, y2] for c in range(x + 1, x2)]
    ys = [[x, y, x2, c] for c in range(y + 1, y2)]
    out = xs + ys
    if not out:
        return np.empty((0, 4), dtype=np.int64)
    return np.asarray(out, dtype=np.int64)


def _can_be_split(rect: np.ndarray) -> bool:
    """Strictly greater than two minimum cells on either axis
    (EvenSplitPartitioner.scala:168-171)."""
    return bool((rect[X2] - rect[X] > 2) or (rect[Y2] - rect[Y] > 2))


def _complement(box: np.ndarray, boundary: np.ndarray) -> np.ndarray:
    """The boundary region not covered by `box`; box must share the
    bottom-left corner and span one full axis (EvenSplitPartitioner.scala
    :128-143)."""
    if not (box[X] == boundary[X] and box[Y] == boundary[Y]):
        raise ValueError("unequal rectangle")
    if not (boundary[X2] >= box[X2] and boundary[Y2] >= box[Y2]):
        raise ValueError("rectangle is smaller than boundary")
    if box[Y2] == boundary[Y2]:
        return np.array([box[X2], box[Y], boundary[X2], boundary[Y2]], dtype=np.int64)
    if box[X2] == boundary[X2]:
        return np.array([box[X], box[Y2], boundary[X2], boundary[Y2]], dtype=np.int64)
    raise ValueError("rectangle is not a proper sub-rectangle")


def partition_cells(
    cells: np.ndarray,
    counts: np.ndarray,
    max_points_per_partition: int,
) -> List[Tuple[np.ndarray, int]]:
    """Split the bounding box of integer `cells` into partitions holding at
    most `max_points_per_partition` points each (best-effort).

    cells: [C, 2] int lower-left cell indices (from geometry.cell_index);
    counts: [C] per-cell point counts. Returns [(int rect [4], count)] in the
    reference's output order (EvenSplitPartitioner.scala:44-64), zero-count
    partitions dropped. Invariant: partition rects tile the bounding box and
    the counts sum to counts.sum() (exact arithmetic; checked).
    """
    cells = np.asarray(cells, dtype=np.int64).reshape(-1, 2)
    counts = np.asarray(counts, dtype=np.int64).reshape(-1)
    if cells.shape[0] == 0:
        return []

    bounding = np.array(
        [
            cells[:, 0].min(),
            cells[:, 1].min(),
            cells[:, 0].max() + 1,
            cells[:, 1].max() + 1,
        ],
        dtype=np.int64,
    )
    total = int(counts.sum())
    # Each entry carries the indices of its cells: splits partition the cell
    # set exactly (unit cells never straddle an integer cut), so candidate
    # evaluation only ever touches the cells of the rect being split.
    remaining: List[Tuple[np.ndarray, int, np.ndarray]] = [
        (bounding, total, np.arange(cells.shape[0]))
    ]
    done: List[Tuple[np.ndarray, int]] = []

    while remaining:
        rect, count, idx = remaining.pop(0)
        if count > max_points_per_partition and _can_be_split(rect):
            x, y, x2, y2 = (int(v) for v in rect)
            cx, cy, w = cells[idx, 0], cells[idx, 1], counts[idx]
            cand_counts = _candidate_counts(rect, cx, cy, w)
            half = count // 2
            cost = np.abs(half - cand_counts)
            best = int(np.argmin(cost))  # first minimum: first-win on ties
            n_xcuts = x2 - x - 1
            if best < n_xcuts:  # x-cut at c = x + 1 + best
                split1 = np.array([x, y, x + 1 + best, y2], dtype=np.int64)
                in1 = (cx - x) <= best
            else:  # y-cut at c = y + 1 + (best - n_xcuts)
                j = best - n_xcuts
                split1 = np.array([x, y, x2, y + 1 + j], dtype=np.int64)
                in1 = (cy - y) <= j
            split2 = _complement(split1, rect)
            c1 = int(cand_counts[best])
            c2 = count - c1  # exact: cells partition between the two halves
            # Depth-first, first half first (s1 :: s2 :: rest).
            remaining[:0] = [(split1, c1, idx[in1]), (split2, c2, idx[~in1])]
        else:
            if count > max_points_per_partition:
                logger.warning(
                    "Can't split: (%s -> %d) (maxSize: %d)",
                    rect,
                    count,
                    max_points_per_partition,
                )
            done.append((rect, count))

    # Reference prepends each finished rect (:94-99) -> reverse completion
    # order; then drops empties (:63).
    out = [(r, c) for (r, c) in reversed(done) if c > 0]
    assert sum(c for _, c in out) == total, "partitioner lost points"
    return out


def partition(
    cells: np.ndarray,
    counts: np.ndarray,
    max_points_per_partition: int,
    minimum_rectangle_size: float,
) -> List[Tuple[np.ndarray, int]]:
    """Reference-shaped float API (EvenSplitPartitioner.partition,
    EvenSplitPartitioner.scala:28-35): cells as [C, 4] float rects aligned to
    a `minimum_rectangle_size` grid. Converts to the exact integer domain,
    partitions there, and converts back (corners become exact
    index * minimum_rectangle_size products)."""
    cells = np.asarray(cells, dtype=np.float64).reshape(-1, 4)
    counts = np.asarray(counts, dtype=np.int64).reshape(-1)
    if cells.shape[0] == 0:
        return []
    idx = np.rint(cells[:, :2] / minimum_rectangle_size).astype(np.int64)
    recon = idx * minimum_rectangle_size
    atol = 1e-9 * max(1.0, minimum_rectangle_size)
    extents = cells[:, 2:] - cells[:, :2]
    if not np.allclose(recon, cells[:, :2], rtol=0, atol=atol) or not np.allclose(
        extents, minimum_rectangle_size, rtol=0, atol=atol
    ):
        raise ValueError(
            "cells are not minimum_rectangle_size-sized rects aligned to the "
            "grid; use partition_cells with integer indices instead"
        )
    parts = partition_cells(idx, counts, max_points_per_partition)
    return [
        (np.asarray(r, dtype=np.float64) * minimum_rectangle_size, c)
        for (r, c) in parts
    ]
