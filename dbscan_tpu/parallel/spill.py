"""Metric spill partitioning: spatial decomposition for high-dim metrics.

The reference's decomposition is 2-D rectangles on a 2eps grid
(EvenSplitPartitioner.scala:66-103 + the eps-halo growth,
DBSCAN.scala:119,132-137) — meaningless for 512-d embeddings. This module
supplies the high-dimensional analog with the SAME correctness contract:
every point pair the kernel can accept ends up together in at least one
partition, so the per-partition kernels + doubly-labeled merge
(parallel/driver.py steps 5-9) reconstruct the global clustering exactly.

Construction (recursive, multiway): pick ``m`` pivots by farthest-point
traversal, assign each point to its nearest pivot (a Voronoi cell), and
COPY each point into every cell c with ``d_c(p) <= r_c + halo``, where
``r_c`` is the radius of c's ASSIGNED points (max pivot distance among
points whose nearest pivot is c). Coverage proof is one triangle
inequality — for any pair p, q with dist(p, q) <= halo and q assigned to
cell c: ``d_c(p) <= d_c(q) + halo <= r_c + halo``, so p is copied into c
and the pair shares it (inductively at every level down to q's home
leaf). Recurse into each cell until ``maxpp``. For the cosine metric the
kernel-accepted pairs have cos_dist <= eps, i.e. chord =
sqrt(2 * cos_dist) <= sqrt(2 * eps) on the normalized vectors, so
``halo = sqrt(2*eps)`` plus a slack covering the kernel's f32/bf16
quantization, and all pivot distances are chords — one matmul against
the pivots per node.

The data-dependent ``r_c + halo`` band matters: the classic
data-independent rule ``d_min + 2*halo`` is vacuous whenever 2*halo
approaches the data diameter — exactly the nonnegative (TF-IDF) case,
where every similarity is >= 0, the whole space fits in a sqrt(2)-chord
ball, and 2*sqrt(2*eps) >= 0.89 for any useful eps. Cell radii track the
ACTUAL cluster spread instead, so tight topics at near-orthogonal
separation still split cleanly.

Why pivots instead of hyperplane cuts: projection onto one direction is
1-Lipschitz, so a cut's halo must be the FULL chord width, while the
data's 1-D projected spread contracts by ~sqrt(D) — in high dimensions
with many clusters no 2*halo window is ever empty. Pivot distances
don't contract: separated clusters keep their full chord separation to
every pivot, so the spill band ``d_min + 2*halo`` stays inside the home
cluster and duplication is ~zero for clusterable data. Farthest-point
pivots keep pivots >> 2*halo apart wherever the data allows it (two
pivots inside one cluster would duplicate that whole cluster into both
cells).

Sets that cannot be usefully split — every pivot within ~2*halo of every
point (data concentrated inside ~one eps-ball, where DBSCAN structure is
trivial anyway) — are emitted as oversized leaves, mirroring the
reference's "Can't split" warning (EvenSplitPartitioner.scala:90); the
driver's dense width guard decides whether those are payable.

Unlike the 2-D grid path there are no rectangles, so the driver derives
merge-band membership purely from instance multiplicity: a point with one
instance is interior to its home leaf (an accepted neighbor in another
leaf would have spilled it); a point with several instances takes the
reference's merge-candidate route (DBSCAN.scala:161-173).
"""

from __future__ import annotations

import logging
import os
from typing import Tuple

import numpy as np

from dbscan_tpu import config, faults, obs

logger = logging.getLogger(__name__)

# A node whose spill pass duplicates more than this (instances / points)
# is declared unsplittable after the pivot-count escalation retries and
# becomes a leaf.
MAX_DUP_FACTOR = 1.6
# A child swallowing more than this fraction of its parent makes no
# progress; counts as a failed split.
MAX_CHILD_FRAC = 0.95
# Pivot-count ceiling per node; retries DOUBLE the pivot count (fewer
# pivots than natural clusters merges clusters into one cell whose
# radius swallows the node — more pivots is the fix, and the
# halo-separation filter collapses any excess benignly), bounded by this
# and by the [node, m] f32 distance matrix staying under ~2 GB.
_MAX_PIVOTS = 192
_MEMBER_BUDGET = 5 * 10**8  # elements of the [node, m] distance matrix
# Concentration signature (see the rejection-screen comment in
# _spill_tree): duplication this far past the budget with most cells'
# bands covering each point means escalation cannot help. ONE set of
# constants shared with the level-synchronous build
# (spill_device.build_level_tree) so host and device trees stop
# escalating at the same points.
SCREEN_DUP_MARGIN = 1.15
CONCENTRATION_CELL_FRAC = 0.5


def pivot_escalation(count: int, attempt: int, maxpp: int) -> int:
    """Pivot count for one node at escalation ``attempt`` — THE split
    policy's m formula, shared verbatim by the host recursion and the
    level-synchronous device build: base 2x the leaf quotient, doubled
    per retry, capped by _MAX_PIVOTS and the member-matrix budget."""
    base_m = max(4, -(-count // maxpp) * 2)
    return int(
        min(
            base_m << attempt,
            _MAX_PIVOTS,
            max(4, _MEMBER_BUDGET // max(1, count)),
        )
    )
# Pivot selection (farthest-point + Lloyd) runs on at most this many
# sampled rows per node; the exact membership pass still sees every row.
_PIVOT_SAMPLE = 65536


class _DenseOps:
    """Unit-row primitives over a dense [N, D] f32 array. All chord
    arithmetic goes through dot products (rows are unit, so
    chord^2 = 2 - 2*dot), which is also the only form a sparse matrix
    can supply — the one abstraction both storage layouts share.
    ``take`` materializes a node's row subset ONCE; every per-node
    primitive then works on that copy (row indices are node-local)."""

    def __init__(self, x: np.ndarray):
        self.x = np.ascontiguousarray(x, dtype=np.float32)
        self.dim = self.x.shape[1]

    def take(self, idx: np.ndarray) -> "_DenseOps":
        return _DenseOps(self.x[idx])

    def dot_all(self, vecs: np.ndarray) -> np.ndarray:
        """[n_node, m] inner products against dense unit vectors."""
        return self.x @ vecs.T

    def dense_rows(self, rows: np.ndarray) -> np.ndarray:
        return self.x[rows]

    def cell_sums_all(self, assign: np.ndarray, m: int) -> np.ndarray:
        sums = np.zeros((m, self.dim), dtype=np.float32)
        np.add.at(sums, assign, self.x)
        return sums


class _SparseOps:
    """Same primitives over a scipy CSR matrix (unit rows). Pivot vectors
    stay dense ([m, D], m <= _MAX_PIVOTS) — only row data is sparse."""

    def __init__(self, x_csr):
        import scipy.sparse as sp

        self.x = sp.csr_matrix(x_csr, dtype=np.float32)
        self.dim = self.x.shape[1]
        self._sp = sp

    def take(self, idx) -> "_SparseOps":
        return _SparseOps(self.x[idx])

    def dot_all(self, vecs):
        return np.asarray(self.x @ vecs.T)

    def dense_rows(self, rows):
        return np.asarray(self.x[rows].todense(), dtype=np.float32)

    def cell_sums_all(self, assign, m):
        sel = self._sp.csr_matrix(
            (
                np.ones(self.x.shape[0], dtype=np.float32),
                (assign, np.arange(self.x.shape[0])),
            ),
            shape=(m, self.x.shape[0]),
        )
        return np.asarray((sel @ self.x).todense(), dtype=np.float32)


def chord_halo(eps: float, quantization: float, dim: int = 0) -> float:
    """Spill halo (chord units) for a cosine threshold: accepted pairs
    have measured cos_dist <= eps + quantization, plus an absolute slack
    covering the f32 pivot-chord rounding on the SPILL side. The kernel
    quantization term does not cover that error: _chords accumulates up
    to delta_s ~ dim * 2^-24 dot error in its f32 matmul. At chord c the
    induced chord error is sqrt(c^2 + 2*delta_s) - c — worst at SMALL c
    (r_c of a tight cell, d_min of near pivots), where it approaches
    sqrt(2*delta_s). Bound it absolutely by sqrt(dim * 2^-24): covers
    every chord magnitude, and stays tiny relative to the halo
    (~5.5e-3 at D=512 vs base ~0.2 at eps 0.02)."""
    base = float(np.sqrt(2.0 * (eps + quantization)))
    slack = float(np.sqrt(dim * 2.0**-24)) + 1e-6
    return base + slack


def band_membership(
    part_ids: np.ndarray,
    point_idx: np.ndarray,
    home_of: np.ndarray,
    n: int,
):
    """Merge classification for spill instance tables: a point with one
    instance is interior to its home leaf (an accepted neighbor in
    another leaf would have spilled it); a multi-instance point takes
    the reference's merge-candidate route on every instance
    (DBSCAN.scala:161-173). Returns (cand [M], inst_inner [M])."""
    multi = np.bincount(point_idx, minlength=n) > 1
    cand = multi[point_idx]
    inst_inner = (home_of[point_idx] == part_ids) & ~cand
    return cand, inst_inner


def _chords(sub, vecs: np.ndarray) -> np.ndarray:
    """[n_node, m] chord distances to unit pivot vectors."""
    d = 2.0 - 2.0 * sub.dot_all(vecs)
    np.clip(d, 0.0, None, out=d)
    np.sqrt(d, out=d)
    return d


def _membership(d: np.ndarray, halo: float):
    """Spill membership from a [n, m] chord matrix: (assign, d_min, r,
    member). ``r_c`` is the radius of each cell's ASSIGNED points (cells
    nobody is assigned to need no copies at all — -inf empties them).
    Both bands are supersets of the needed copy-set (every cell holding a
    point within halo of p), so their INTERSECTION is too: the radius
    band ``r_c + halo`` survives the nonnegative (TF-IDF) regime where
    2*halo swamps the data diameter, while the classic ``d_min + 2*halo``
    band caps cells whose radius was inflated by an assigned outlier.
    ONE implementation shared by the exact full-node pass and the sampled
    rejection screen — the screen's only-rejects-what-the-exact-pass-
    rejects invariant depends on the two using the same band formula."""
    assign = np.argmin(d, axis=1)
    d_min = d[np.arange(len(d)), assign]
    r = np.full(d.shape[1], -np.inf)
    np.maximum.at(r, assign, d_min)
    member = (d <= (r[None, :] + halo)) & (
        d <= (d_min + 2.0 * halo)[:, None]
    )
    return assign, d_min, r, member


def _chords_of(rows: np.ndarray, vecs: np.ndarray) -> np.ndarray:
    """Same chord math over raw unit-row blocks (the greedy-leader path
    slices node arrays directly instead of materializing sub-ops)."""
    d = 2.0 - 2.0 * (rows @ vecs.T)
    np.clip(d, 0.0, None, out=d)
    np.sqrt(d, out=d)
    return d


def _farthest_pivots(sub, m: int, rng) -> np.ndarray:
    """Greedy max-min (farthest-point) pivot VECTORS: start random, then
    repeatedly take the point farthest from the chosen set. Keeps pivots
    as far apart as the data allows — the property that stops two pivots
    from landing inside one cluster and duplicating it wholesale."""
    first = int(rng.integers(sub.x.shape[0]))
    vecs = [sub.dense_rows(np.array([first]))[0]]
    d = _chords(sub, np.stack(vecs))[:, 0]
    for _ in range(m - 1):
        nxt = int(np.argmax(d))
        if d[nxt] <= 0.0:
            break  # remaining points identical to a pivot
        vecs.append(sub.dense_rows(np.array([nxt]))[0])
        nd = _chords(sub, vecs[-1][None, :])[:, 0]
        np.minimum(d, nd, out=d)
    return np.stack(vecs)


def _pivot_vectors(sub, m: int, halo: float, rng):
    """Pivot VECTORS for one node: farthest-point seeds (max spread, but
    they gravitate to outliers/noise) refined by two Lloyd steps
    (nearest-pivot means, renormalized to the sphere) that pull each
    pivot into the mass of its cell — cluster centers, not stragglers —
    then MERGED so survivors are pairwise > halo apart: two pivots inside
    one halo ball cannot separate anything (each other's cells sit inside
    the spill bands and duplicate wholesale), they only multiply the
    duplication. The covering proof only needs pivots to be points of
    the metric space, so synthetic unit vectors are fine. Empty cells
    drop out."""
    p = _farthest_pivots(sub, m, rng)
    if len(p) < 2:
        return p
    for _ in range(2):
        a = np.argmax(sub.dot_all(p), axis=1)  # nearest = max cos sim
        sums = sub.cell_sums_all(a, len(p))
        norms = np.linalg.norm(sums, axis=1)
        keep = norms > 1e-12
        if keep.sum() < 2:
            break
        p = sums[keep] / norms[keep][:, None]
    a = np.argmax(sub.dot_all(p), axis=1)
    return halo_separation_filter(
        p, np.bincount(a, minlength=len(p)), halo
    )


def halo_separation_filter(
    p: np.ndarray, mass: np.ndarray, halo: float
) -> np.ndarray:
    """Greedy halo-separation filter shared by the host recursion and
    the node-recursive device path (farthest-point seed order is lost
    after Lloyd, so re-derive): keep pivots in descending cell-mass
    order, dropping any within halo chord of a kept one. Pivot parity
    BETWEEN THOSE TWO paths depends on this being their one
    implementation; the level-synchronous build runs its own batched
    twin ON DEVICE (spill_device._make_level_build's hstep loop, same
    policy, per-node in parallel) — a policy change here must be
    mirrored there (different pivots stay label-safe either way:
    canonical merge ids, PARITY.md "Spill tree")."""
    order = np.argsort(-mass)
    kept: list = []
    for j in order:
        pj = p[j]
        ok = True
        for kidx in kept:
            chord2 = float(((pj - p[kidx]) ** 2).sum())
            if chord2 <= halo * halo:
                ok = False
                break
        if ok:
            kept.append(j)
    return p[np.array(kept, dtype=np.int64)]


# Leader-cover pre-split (dense concentration regime) bounds: leader cap
# per node (the O(n * L * D) passes must stay host-affordable; the cap-hit
# retry DOUBLES the cover radius), and a canopy-overlap budget in
# covering-leaders-per-point — heavy overlap means the data is not
# separated at this radius and larger radii only overlap more, so the
# node returns to the pivot tree.
_LEADER_CAP = 4096
_LEADER_EDGE_BUDGET = 32
_LEADER_CHUNK = 1 << 16


# uncovered candidates resolved per pairwise block in the host greedy
# cover: bounds the [k, k] chord matrix at ~1 MB while keeping the
# per-candidate BLAS calls batched away
_LEADER_RESOLVE = 512


def _greedy_leaders(sub: "_DenseOps", t: float, rng):
    """Greedy metric cover of the node at radius ``t``: stream shuffled
    batches, points farther than ``t`` from every existing leader become
    leaders themselves (sequential within the batch so co-batched
    near-duplicates collapse to one). Returns the [L, D] leader rows, or
    None when L would exceed _LEADER_CAP. Batches grow adaptively while
    no new leaders appear (coverage checks are one matmul) and shrink
    back on discovery, keeping the sequential tail short.

    The in-batch greedy is resolved in BLOCKS (the host counterpart of
    the device cover's [K, K] resolution, spill_device._make_cover):
    each ``_LEADER_RESOLVE``-candidate block pays one matmul against the
    leaders this batch minted so far plus one [k, k] pairwise pass, and
    the sequential walk then runs over the precomputed matrix — the
    per-candidate [1, L] BLAS calls the old inner loop issued (one
    device-shaped sync per point in the worst case) collapse into two
    batched passes per block, with decisions identical to the
    one-at-a-time walk."""
    n = sub.x.shape[0]
    order = rng.permutation(n)
    buf = np.empty((_LEADER_CAP, sub.dim), dtype=np.float32)
    nb = 0  # leaders stored in buf[:nb]
    batch = 2048
    s = 0
    while s < n:
        rows = order[s : s + batch]
        s += len(rows)
        vb = sub.x[rows]
        if nb:
            d = _chords_of(vb, buf[:nb])
            unc = np.flatnonzero(d.min(axis=1) > t)
        else:
            unc = np.arange(len(vb))
        if len(unc) == 0:
            batch = min(batch * 2, _LEADER_CHUNK)
            continue
        batch = 2048
        start = nb  # pre-batch leaders already filtered via d above
        for s2 in range(0, len(unc), _LEADER_RESOLVE):
            blk = vb[unc[s2 : s2 + _LEADER_RESOLVE]]
            if nb > start:
                # drop candidates covered by leaders minted earlier in
                # THIS batch (exactly the walk's first check), one
                # batched pass instead of one matvec per candidate
                alive = (
                    _chords_of(blk, buf[start:nb]).min(axis=1) > t
                )
                blk = blk[alive]
            if not len(blk):
                continue
            pair = _chords_of(blk, blk)
            kept: list = []
            for j in range(len(blk)):
                # identical to the sequential walk: candidate j drops
                # iff an EARLIER in-block keeper covers it
                if kept and float(pair[j, kept].min()) <= t:
                    continue
                if nb >= _LEADER_CAP:  # only a real append overflows
                    return None
                buf[nb] = blk[j]
                nb += 1
                kept.append(j)
    return buf[:nb].copy()


def leader_components(sub: "_DenseOps", halo: float, rng):
    """Exact-cover pre-split for DENSE unit rows in the concentration
    regime (cluster count >> pivot count, all cross-cluster chords
    ~equal — e.g. hundreds of tight blobs at near-orthogonal directions,
    where every pivot band spills wholesale). The dense counterpart of
    ``prefix_components``.

    Cover proof: greedy leaders at radius T guarantee every point is
    within T of some leader. For any accepted pair p, q (chord <= halo)
    and any leader L covering p: d(q, L) <= T + halo, so BOTH endpoints
    lie in L's (T + halo)-canopy. Leaders whose (T + halo)-canopies share
    a point are unioned, therefore p's and q's assigned leaders (their
    nearest, both within d <= T <= T + halo of the shared canopy's
    leader) land in one component — every accepted pair is intra-
    component, components are exact covers, ZERO halo duplication.

    Separated data keeps canopies disjoint across clusters, so the
    components are the clusters (plus noise singletons). Heavily
    overlapping data either exceeds the covering-leader budget or
    collapses to one component — both return None and the node falls
    back to the pivot tree / oversized-leaf route unchanged.
    """
    n = sub.x.shape[0]
    for t_mult in (2.0, 4.0, 8.0):
        t = t_mult * halo
        if t + halo >= 1.9:  # canopies span the sphere: hopeless
            break
        leaders = _greedy_leaders(sub, t, rng)
        if leaders is None:
            continue  # cap exceeded: retry at a coarser radius
        if len(leaders) < 2:
            return None
        band = t + halo
        nearest = np.empty(n, dtype=np.int64)
        ea_l, eb_l = [], []
        over_budget = False
        # bound the [chunk, L] chord transient to ~64 MiB however many
        # leaders landed (at the 4096 cap a fixed 2^16 chunk would be a
        # 1 GiB host allocation — scale rows inversely with L instead)
        chunk = max(1024, min(_LEADER_CHUNK, (1 << 24) // max(1, len(leaders))))
        # the edge budget is judged CUMULATIVELY against the total row
        # allowance, not per chunk: a per-chunk test would get noisier as
        # the chunk shrinks (one locally dense window tripping it), while
        # the cumulative form accepts/rejects independently of chunk size
        # and still exits early once the whole-node allowance is blown
        edges_seen = 0
        for s in range(0, n, chunk):
            d = _chords_of(sub.x[s : s + chunk], leaders)
            nearest[s : s + len(d)] = np.argmin(d, axis=1)
            mask = d <= band
            edges_seen += int(mask.sum())
            if edges_seen > _LEADER_EDGE_BUDGET * n:
                over_budget = True
                break
            multi = mask.sum(axis=1) > 1
            if multi.any():
                rows, cols = np.nonzero(mask[multi])
                row_change = np.r_[True, rows[1:] != rows[:-1]]
                ea_l.append(cols[row_change][np.cumsum(row_change) - 1])
                eb_l.append(cols)
        if over_budget:
            # canopies already overlap heavily; larger radii overlap more
            return None
        ea = np.concatenate(ea_l) if ea_l else np.empty(0, np.int64)
        eb = np.concatenate(eb_l) if eb_l else np.empty(0, np.int64)

        from dbscan_tpu.parallel.graph import uf_components

        n_comp, gids = uf_components(ea, eb, len(leaders))
        if n_comp < 2:
            return None
        comp = (np.asarray(gids)[nearest] - 1).astype(np.int32)
        return comp, int(n_comp)
    return None


# Candidate-pair budget for prefix_components, in pairs-per-doc (counted
# pre-dedup): past it the prefix index is too dense to verify cheaply
# (stopword-heavy data) and the caller falls back to the pivot tree.
# Expansion, dedup, and verification run in bounded chunks, so the budget
# caps time, not memory.
_PREFIX_PAIR_BUDGET = 256
_PREFIX_CHUNK = 1 << 22  # candidate pairs per verify chunk
# elevated budget for the last-resort retry inside the pivot tree
# (when the tree itself failed to split, verification is the only
# remaining move and is worth ~16x more pair work)
_PREFIX_RETRY_BUDGET = 4096


def prefix_components(x_csr, t: float, budget: int = None):
    """Exact-cover pre-split for SPARSE unit rows: connected components of
    the VERIFIED dot >= t graph, found via prefix filtering.

    Symmetric prefix filter (the AllPairs/PPJoin bound, re-derived): fix
    any global feature order and let prefix(x) be the head of x's
    features (in that order) kept until the remaining tail norm drops
    below ``t``. For a pair with dot(x, y) >= t, let f* be their FIRST
    shared feature: every shared feature sits at-or-after f*, so
    dot <= ||x at-or-after f*|| and dot <= ||y at-or-after f*|| — both
    tails still carry norm >= t at f*, hence f* lies in BOTH prefixes.
    So every qualifying pair appears inside some feature's prefix list —
    the candidate pairs. Candidates are then VERIFIED with exact f64
    dots before union (sharing a rare prefix feature is necessary, not
    sufficient: blind unions percolate through incidental shares), which
    makes the components exactly the dot >= t graph's components — the
    finest partition no qualifying pair crosses, with ZERO halo
    duplication. This splits the concentration regime (cluster count >>
    pivot count, all cross distances ~equal) where the pivot tree
    cannot.

    The global order is rarest-feature-first (ascending document
    frequency), keeping per-feature prefix lists small. If the candidate
    pair count exceeds ``_PREFIX_PAIR_BUDGET * n`` (stopword-heavy
    prefixes), returns None and the caller falls back to the pivot tree.
    Returns (comp [N] int32 0-based dense ids, n_comp) otherwise; None
    also when t <= 0 (prefixes would cover every feature).
    """
    if t <= 0.0:
        return None
    import scipy.sparse as sp

    # f64 working copy: prefix sums and verification dots are computed
    # exactly over the stored values (f32 inputs round the VALUES, which
    # chord_halo's quantization slack already covers — the margins here
    # only need to absorb rows being unit to ~1e-6, not exactly)
    x = sp.csr_matrix(x_csr, dtype=np.float64)
    n, d = x.shape
    if n == 0 or x.nnz == 0:
        return None
    df = x.getnnz(axis=0)
    rank = np.empty(d, dtype=np.int64)
    rank[np.lexsort((np.arange(d), df))] = np.arange(d)
    rows = np.repeat(np.arange(n, dtype=np.int64), np.diff(x.indptr))
    if n * d < 2**62:
        order = np.argsort(rows * d + rank[x.indices], kind="stable")
    else:  # astronomically wide: exact 2-key sort
        order = np.lexsort((rank[x.indices], rows))
    r_sorted = rows[order]
    v2 = x.data[order] ** 2
    # per-row sum of squares BEFORE each nnz position (global cumsum
    # minus the row's starting cumsum); the prefix condition
    # ||tail from i|| >= t is tested against the row's ACTUAL total
    # (f32-normalized rows are unit only to ~1e-6), with a relative
    # margin that chord_halo's slack dwarfs
    cum0 = np.r_[0.0, np.cumsum(v2)]
    row_start = np.searchsorted(r_sorted, np.arange(n))
    row_end = np.searchsorted(r_sorted, np.arange(1, n + 1))
    row_total = cum0[row_end] - cum0[row_start]
    before = cum0[:-1] - cum0[row_start[r_sorted]]
    tail = row_total[r_sorted] - before
    keep = tail >= (t * t) * (1.0 - 1e-5)
    pf = x.indices[order][keep]
    pr = r_sorted[keep]
    o2 = np.argsort(pf, kind="stable")
    pf, pr = pf[o2], pr[o2]

    # candidate pairs: all doc pairs within each feature's prefix list
    bounds = np.flatnonzero(np.r_[True, pf[1:] != pf[:-1], True])
    sizes = np.diff(bounds)
    pairs_per_group = sizes * (sizes - 1) // 2
    if budget is None:
        budget = _PREFIX_PAIR_BUDGET
    if int(pairs_per_group.sum()) > budget * n:
        return None

    # expand -> dedup -> verify in bounded blocks: only PASSING edges
    # (few) accumulate, so memory stays bounded by the block no matter
    # the total candidate count — including within one oversized group,
    # whose row-bands are expanded incrementally rather than via a full
    # triu materialization. Cross-block duplicate edges are harmless to
    # the union-find.
    pa_l, pb_l = [], []
    pending = 0
    any_edge = [False]

    # Incremental union-find screen: only edges that could still MERGE
    # components pay for exact verification. Candidate lists put every
    # intra-topic pair in the queue (~budget*n of them), but once a
    # component is connected every further pair inside it is redundant —
    # union is idempotent, so skipping already-connected pairs cannot
    # change the final components while it eliminates the dominant cost
    # (the CSR row-gather + multiply of verification: measured 497 s of
    # a 524 s spill at 200k docs before this screen).
    parent = np.arange(n, dtype=np.int64)

    # INVARIANT: outside _union_edges, ``parent`` is fully flattened
    # (parent[parent] == parent), so a root lookup is ONE gather. The
    # doc count n is tiny next to the candidate-id streams (millions of
    # pairs screened per _verify), so paying an O(n)-per-round flatten
    # inside the union to make every screen a single gather is the
    # cheap side of the trade — the old per-id path walk re-traversed
    # chains across multi-million-element arrays.
    def _roots(ids):
        return parent[ids]

    def _flatten_parent():
        while True:
            pp = parent[parent]
            if np.array_equal(pp, parent):
                return
            parent[:] = pp

    def _union_edges(a, b):
        """Batch-union accepted edges — vectorized min-root hooking
        instead of the old per-edge interpreted loop (measured as one of
        the dominant costs of the 200k-doc sparse spill: ~3.4 s of
        Python union-find plus the chains it left for _roots). Each
        round resolves roots for every pending pair at once, attaches
        each greater root to the SMALLEST peer root observed for it
        (parent values only ever decrease, so chains stay acyclic), and
        re-queues the merged pairs — chains collapse in O(log) rounds.
        Decisions are order-independent: union is idempotent and the
        final components equal the sequential walk's."""
        a = np.asarray(a, dtype=np.int64)
        b = np.asarray(b, dtype=np.int64)
        while len(a):
            ra = parent[a]  # flattened ⇒ roots
            rb = parent[b]
            live = ra != rb
            if not live.any():
                return
            ra, rb = ra[live], rb[live]
            lo = np.minimum(ra, rb)
            hi = np.maximum(ra, rb)
            order = np.argsort(hi, kind="stable")
            hi_s, lo_s = hi[order], lo[order]
            starts = np.flatnonzero(np.r_[True, hi_s[1:] != hi_s[:-1]])
            min_lo = np.minimum.reduceat(lo_s, starts)
            tgt = hi_s[starts]
            parent[tgt] = np.minimum(parent[tgt], min_lo)
            _flatten_parent()  # restore the single-gather invariant
            # EVERY live edge stays queued until its endpoints share a
            # root: the hooking above applied only each group's minimum
            # edge, and dropping the rest would under-merge this round
            # (correct only eventually, via re-verified duplicate dots
            # — measured 3.3x the verification volume)
            a, b = lo, hi

    def _verify():
        nonlocal pending
        if not pa_l:
            return
        lo_ = np.concatenate(pa_l)
        hi_ = np.concatenate(pb_l)
        pa_l.clear()
        pb_l.clear()
        pending = 0
        lo = np.minimum(lo_, hi_)
        hi = np.maximum(lo_, hi_)
        # union-find screen BEFORE the packed-key dedup: once a
        # component is connected every further intra pair is redundant,
        # and candidate lists are dominated by exactly those — screening
        # first makes the sort/unique cost proportional to the LIVE
        # pairs instead of the raw candidate stream (measured ~12 s of
        # unique+sort at 200k docs pre-screen)
        live = _roots(lo) != _roots(hi)
        lo, hi = lo[live], hi[live]
        if not len(lo):
            return
        uniq = np.unique(lo * np.int64(n) + hi)
        ua, ub = np.divmod(uniq, np.int64(n))
        # SMALL dot batches, screened per batch: pairs are sorted by
        # (lo, hi), so one component's candidates are adjacent — after
        # the first batch connects it, the per-batch root screen kills
        # the rest of its pairs BEFORE they pay the CSR gather+multiply.
        # One big batch would dot a whole component's pair list (~k^2)
        # before any union could prune (measured 3.3x the verification
        # volume at 200k docs); the batch size trades that against
        # per-call scipy overhead.
        bs = 4096
        for s in range(0, len(ua), bs):
            a = ua[s : s + bs]
            b = ub[s : s + bs]
            live = _roots(a) != _roots(b)
            if not live.any():
                continue
            a, b = a[live], b[live]
            dots = np.asarray(x[a].multiply(x[b]).sum(axis=1)).ravel()
            ok = dots >= t - 1e-9
            any_edge[0] |= bool(ok.any())
            _union_edges(a[ok], b[ok])

    def _pair_blocks(docs):
        """All unordered pairs of ``docs``, yielded in <=_PREFIX_CHUNK
        blocks (row-band expansion for oversized groups)."""
        g = len(docs)
        if g * (g - 1) // 2 <= _PREFIX_CHUNK:
            ii, jj = np.triu_indices(g, k=1)
            yield docs[ii], docs[jj]
            return
        i = 0
        while i < g - 1:
            take = max(1, _PREFIX_CHUNK // max(1, g - i - 1))
            idx = np.arange(i, min(g - 1, i + take))
            counts = g - idx - 1
            ii = np.repeat(idx, counts)
            run_start = np.repeat(np.r_[0, np.cumsum(counts)[:-1]], counts)
            jj = np.repeat(idx + 1, counts) + (
                np.arange(counts.sum()) - run_start
            )
            yield docs[ii], docs[jj]
            i = idx[-1] + 1

    for gi in range(len(sizes)):
        if sizes[gi] < 2:
            continue
        for a_blk, b_blk in _pair_blocks(pr[bounds[gi] : bounds[gi + 1]]):
            # source screen: a topic's pairs recur across every feature
            # in its prefix (~row-nnz times) — once one group's pairs
            # are verified and unioned, the repeats die HERE for one
            # root gather instead of riding the pending buffers into
            # _verify's concat/min/max/unique passes (measured as the
            # dominant _verify cost at 200k docs)
            live = _roots(a_blk) != _roots(b_blk)
            if not live.any():
                continue
            pa_l.append(a_blk[live])
            pb_l.append(b_blk[live])
            pending += int(live.sum())
            if pending >= _PREFIX_CHUNK:
                _verify()
    _verify()
    if not any_edge[0]:
        comp = np.arange(n, dtype=np.int32)
        return comp, n
    # `parent` already IS the verified dot>=t graph's union-find (every
    # accepted edge was unioned; screened-out edges were by construction
    # already connected) — flatten to roots and dense-rank them
    roots = _roots(np.arange(n, dtype=np.int64))
    _u, comp = np.unique(roots, return_inverse=True)
    return comp.astype(np.int32), int(len(_u))


def _component_bins(comp: np.ndarray, n_comp: int, maxpp: int):
    """Group rows by component and bin-pack the fitting components into
    shared groups of capacity maxpp (size-descending next-fit: noise
    singletons would otherwise each become a padded leaf). Returns
    (packed row-index arrays — each sorted ascending, whole components
    only — and oversized components' row arrays). Packing whole
    components together is sound: no qualifying pair crosses components,
    and the halo's slack margin means the quantized kernel cannot accept
    a cross-component pair either."""
    order_c = np.argsort(comp, kind="stable")  # ascending rows per comp
    bounds = np.searchsorted(comp[order_c], np.arange(n_comp + 1))
    sizes = np.diff(bounds)
    packed, oversized = [], []
    small = np.flatnonzero(sizes <= maxpp)
    small = small[np.argsort(sizes[small], kind="stable")[::-1]]
    cur: list = []
    fill = 0
    for c in small:
        g = int(sizes[c])
        if fill and fill + g > maxpp:
            packed.append(np.sort(np.concatenate(cur)))
            cur, fill = [], 0
        cur.append(order_c[bounds[c] : bounds[c + 1]])
        fill += g
    if cur:
        packed.append(np.sort(np.concatenate(cur)))
    for c in np.flatnonzero(sizes > maxpp):
        oversized.append(order_c[bounds[c] : bounds[c + 1]])
    return packed, oversized


def _split_by_components(unit_csr, pc, maxpp: int, halo: float, seed: int):
    """Assemble spill output across prefix components (ZERO duplicated
    instances): packed bins become leaves directly; oversized components
    recurse through spill_partition with part-id offsets. Keeps the
    (partition, point index)-sorted instance layout the packers
    require."""
    comp, n_comp = pc
    n = unit_csr.shape[0]
    packed, oversized = _component_bins(comp, n_comp, maxpp)

    part_ids_l, point_idx_l = [], []
    home = np.empty(n, dtype=np.int32)
    p_off = 0
    for rows_b in packed:
        part_ids_l.append(np.full(len(rows_b), p_off, dtype=np.int64))
        point_idx_l.append(rows_b)
        home[rows_b] = p_off
        p_off += 1
    for rows_c in oversized:
        pid, pidx, np_sub, ho = spill_partition(
            unit_csr[rows_c], maxpp, halo, seed, _presplit=False
        )
        part_ids_l.append(pid + p_off)
        point_idx_l.append(rows_c[pidx])
        home[rows_c] = ho + p_off
        p_off += np_sub
    return (
        np.concatenate(part_ids_l),
        np.concatenate(point_idx_l),
        int(p_off),
        home,
    )


def _spill_device_enabled() -> bool:
    """DBSCAN_SPILL_DEVICE: 1 forces the accelerator spill passes (tests
    exercise them on the CPU backend this way), 0 forces host BLAS,
    auto (default) uses the device exactly when a non-CPU backend is
    live — the single-core host is the measured bottleneck of the
    cosine/sparse rows (VERDICT r4 item 2)."""
    v = config.env("DBSCAN_SPILL_DEVICE")
    if v == "0":
        return False
    if v == "1":
        return True
    from dbscan_tpu.parallel import spill_device as sdev

    return sdev.device_available()


def spill_partition(
    unit, maxpp: int, halo: float, seed: int = 0, _presplit: bool = True,
    device_ops=None, info_out: dict = None,
) -> Tuple[np.ndarray, np.ndarray, int, np.ndarray]:
    """Build the spill partition over ``unit`` [N, D] (rows must be the
    UNIT-NORM coordinates ``halo`` refers to — normalized vectors for
    cosine, so distances are chords computed from inner products). Takes
    a dense ndarray or a scipy sparse matrix (CSR'd internally).

    Returns (part_ids [M], point_idx [M], n_parts, home_of [N]) with the
    instance list sorted by (partition, point index) — the layout the
    packers require (binning.bucketize_grouped) — and ``home_of`` giving
    each point's home leaf (its nearest-pivot chain; exactly one).

    ``info_out`` (optional dict) receives build diagnostics plus the
    leaf LAYOUT the dispatchers consume without re-deriving it:
    ``counts`` ([n_parts] instances per leaf — part_ids is
    partition-major, so offsets are its cumsum), and, when the
    level-synchronous device build ran, ``levels`` /
    ``level_dispatches`` (one fused dispatch per level + the closing
    compact)."""
    if hasattr(unit, "tocsr"):  # scipy sparse input
        unit = unit.tocsr()
        n = unit.shape[0]
        if n > maxpp and _presplit:
            # exact-cover pre-split: accepted pairs have true chord <=
            # halo (chord_halo's construction), i.e. dot >= 1 - halo^2/2
            # — the prefix-filter threshold. Oversized components skip
            # straight to the pivot tree (_presplit=False): components
            # are maximal connected sets of the verified dot >= t graph,
            # which depends only on the vectors, so re-splitting a
            # component can never succeed.
            pc = prefix_components(unit, 1.0 - halo * halo / 2.0)
            if pc is not None and pc[1] > 1:
                out = _split_by_components(unit, pc, maxpp, halo, seed)
                if info_out is not None:
                    info_out["counts"] = np.bincount(
                        out[0], minlength=out[2]
                    )
                return out
        ops = _SparseOps(unit) if n else None
    else:
        unit = np.asarray(unit)
        n = len(unit)
        ops = _DenseOps(unit) if n else None
    if n == 0:
        return (
            np.empty(0, np.int64),
            np.empty(0, np.int64),
            0,
            np.empty(0, np.int32),
        )
    rng = np.random.default_rng(seed)
    # Root span over this (sub)tree build: spill_partition_s is ~97% of
    # the cosine wall on TPU, and the sub-spans below (spill.pivots /
    # spill.screen / spill.membership / spill.leader_cover — now emitted
    # on the HOST paths too, not only the device ones) are what lets
    # obs.analyze attribute the remainder for the next optimization PR.
    with obs.span("spill.partition", n=int(n), maxpp=int(maxpp)):
        return _spill_tree(
            unit, ops, n, maxpp, halo, seed, rng, device_ops, info_out
        )


def _level_tree_enabled() -> bool:
    """DBSCAN_SPILL_DEVICE_TREE: the level-synchronous device build
    (one fused dispatch per tree level, spill_device.build_level_tree).
    On by default wherever the device passes are live; 0 keeps the
    node-recursive path as the parity oracle."""
    return bool(config.env("DBSCAN_SPILL_DEVICE_TREE"))


def _spill_tree(unit, ops, n, maxpp, halo, seed, rng, device_ops,
                info_out=None):
    """The recursive pivot-tree build behind :func:`spill_partition`
    (split out so the root span wraps exactly the tree work)."""
    # Device-resident rows for the accelerated passes (dense only): one
    # bf16 upload of the WHOLE array; every node below gathers its subset
    # on device from it (a child upload is an int32 index vector). Any
    # device failure permanently degrades THIS run to the host path.
    sdev = None
    dev_root = None
    if isinstance(ops, _DenseOps) and n > maxpp:
        if device_ops is not None:
            # caller-provided resident rows (the driver reuses the SAME
            # upload for the leaf-payload gather dispatch)
            from dbscan_tpu.parallel import spill_device as _sdev_mod

            dev_root = device_ops
            sdev = _sdev_mod
        elif _spill_device_enabled():
            try:
                from dbscan_tpu.parallel import spill_device as _sdev_mod

                dev_root = _sdev_mod.DeviceNodeOps.from_host(ops.x)
                sdev = _sdev_mod
            except Exception as e:  # noqa: BLE001 — degrade, don't die
                logger.warning("spill: device passes unavailable (%s)", e)
                dev_root = None
    leaves = []  # (member point rows, home flags)
    stack = [(np.arange(n, dtype=np.int64), np.ones(n, dtype=bool))]
    # Level-synchronous device build (ROADMAP item 2): one fused
    # dispatch per tree LEVEL over all open nodes at once, host
    # involvement only at the split policy ([S, m] size tables) and the
    # final leaf pulls (PullEngine-overlapped). Nodes its pivot policy
    # cannot split come back as fallback items and seed the classic
    # recursion below, which owns the leader-cover / prefix-split /
    # oversized-leaf ladder unchanged. Any failure degrades to the host
    # recursion for the WHOLE build — correctness never depends on the
    # level path.
    if dev_root is not None and n > maxpp and _level_tree_enabled():
        try:
            lv_leaves, lv_fallback = sdev.build_level_tree(
                dev_root, n, maxpp, halo, rng, info=info_out
            )
            leaves.extend(lv_leaves)
            stack = [
                (np.asarray(ix, dtype=np.int64), np.asarray(hm, bool))
                for ix, hm in lv_fallback
            ]
        except Exception as e:  # noqa: BLE001 — degrade, don't die
            logger.warning(
                "spill: level-synchronous device tree failed (%s); "
                "host recursion",
                e,
            )
            faults.note_degrade()
            leaves = []
            stack = [
                (np.arange(n, dtype=np.int64), np.ones(n, dtype=bool))
            ]
    while stack:
        idx, home = stack.pop()
        if len(idx) <= maxpp:
            leaves.append((idx, home))
            continue
        dev_sub = None
        if dev_root is not None:
            try:
                dev_sub = (
                    dev_root if len(idx) == n else dev_root.take(idx)
                )
            except Exception as e:  # noqa: BLE001
                logger.warning("spill: device take failed (%s); host", e)
                dev_root = None
        # host subset materialization only when some pass will need it
        sub = ops.take(idx) if dev_sub is None else None
        split = None
        degenerate = False
        for attempt in range(3):  # retries escalate the pivot count
            m = pivot_escalation(len(idx), attempt, maxpp)
            # pivot SELECTION runs on a sample: farthest-point + Lloyd
            # cost ~m+4 node-wide matmuls, needed only for pivot quality
            # — a 64k sample sees every cluster worth a pivot (smaller
            # ones get theirs when recursion makes them a bigger
            # fraction); the exact full-node pass below is just ONE
            # matmul. Correctness never depends on pivot choice.
            sub_s = None
            dev_s = None
            s_local = None
            if len(idx) > _PIVOT_SAMPLE:
                s_local = rng.choice(
                    len(idx), _PIVOT_SAMPLE, replace=False
                )
            piv = None
            if dev_sub is not None:
                try:
                    dev_s = (
                        dev_sub.take(np.sort(s_local))
                        if s_local is not None
                        else None
                    )
                    with obs.span(
                        "spill.pivots", node=int(len(idx)), m=int(m)
                    ):
                        piv = faults.supervised(
                            faults.SITE_SPILL,
                            lambda _b: sdev.pivot_vectors_device(
                                dev_s if dev_s is not None else dev_sub,
                                m, halo, rng,
                            ),
                            label="pivots",
                        )
                except Exception as e:  # noqa: BLE001 — degrade to host
                    logger.warning("spill: device pivots failed (%s)", e)
                    faults.note_degrade()
                    dev_root = dev_sub = dev_s = None
                    sub = ops.take(idx)
            if piv is None:
                with obs.span(
                    "spill.pivots", node=int(len(idx)), m=int(m),
                    host=True,
                ):
                    if s_local is not None:
                        sub_s = sub.take(np.sort(s_local))
                        piv = _pivot_vectors(sub_s, m, halo, rng)
                    else:
                        piv = _pivot_vectors(sub, m, halo, rng)
            if len(piv) < 2:
                # All pivots collapsed inside one halo ball. For DENSE
                # nodes one exact [n, 1] pass settles the node: if every
                # point is within halo of the surviving pivot, pairwise
                # chords are <= 2*halo <= T + halo, so EVERY leader
                # canopy in leader_components contains every point and
                # the cover is provably ONE component — skip the
                # O(n * leaders) fallback and emit the oversized leaf
                # now (the dense-width guard then fails fast,
                # pre-packing). Nodes with points beyond halo keep the
                # fallback: a leader cover can still split them. Sparse
                # keeps its prefix retry either way: chord <= halo pairs
                # of a 2*halo-diameter node can still form >1 component.
                if isinstance(ops, _DenseOps) and len(piv) == 1:
                    # chunked exact-f32 matvec: no full-node row gather
                    # (a resident-mode 1M x 512 node would otherwise pay
                    # a ~2 GB host copy on this bail path)
                    v = piv[0]
                    min_dot = np.inf
                    # rows-per-chunk scaled by width: ~64 MiB transient
                    # regardless of D (same cap leader_components uses)
                    step = max(1024, (1 << 24) // max(1, ops.dim))
                    for s0 in range(0, len(idx), step):
                        rows = idx[s0 : s0 + step]
                        min_dot = min(
                            min_dot, float(ops.x[rows].dot(v).min())
                        )
                    if 2.0 - 2.0 * min_dot <= halo * halo:
                        degenerate = True
                break  # unsplittable by pivots
            # Cheap rejection screen on the SAME sample before paying the
            # full-node matmul: in the concentration regime (cluster
            # count >> pivots, all cross distances ~equal) every
            # escalation attempt fails, and without the screen each
            # failure costs a full [n_node, m] pass — measured as the
            # dominant share of the cosine anchor's spill time. The
            # sample UNDERESTIMATES duplication (radii from a subset only
            # shrink the bands), so with the 1.15 margin it only rejects
            # attempts the exact pass would reject too; anything the
            # screen lets through is still decided by the exact full-node
            # pass below — correctness and split quality are unchanged.
            if sub_s is not None or dev_s is not None:
                if dev_s is not None:
                    try:
                        with obs.span(
                            "spill.screen", node=int(len(idx))
                        ):
                            screen_dup, screen_m = faults.supervised(
                                faults.SITE_SPILL,
                                lambda _b: sdev.screen_dup_device(
                                    dev_s, piv, halo
                                ),
                                label="screen",
                            )
                    except Exception as e:  # noqa: BLE001
                        logger.warning(
                            "spill: device screen failed (%s); host", e
                        )
                        faults.note_degrade()
                        dev_root = dev_sub = dev_s = None
                        sub = ops.take(idx)
                        sub_s = sub.take(np.sort(s_local))
                        _, _, _, mem_s = _membership(
                            _chords(sub_s, piv), halo
                        )
                        screen_dup = float(mem_s.sum()) / mem_s.shape[0]
                        screen_m = mem_s.shape[1]
                else:
                    with obs.span(
                        "spill.screen", node=int(len(idx)), host=True
                    ):
                        _, _, _, mem_s = _membership(
                            _chords(sub_s, piv), halo
                        )
                    screen_dup = float(mem_s.sum()) / mem_s.shape[0]
                    screen_m = mem_s.shape[1]
                if screen_dup > SCREEN_DUP_MARGIN * MAX_DUP_FACTOR:
                    # Concentration signature: each point lands in MOST
                    # cells' bands (dup per point ~ pivot count), i.e.
                    # every cell radius swallows the node spread. More
                    # pivots cannot shrink radii in this regime (all
                    # cross distances ~equal until pivot count reaches
                    # cluster count, far past _MAX_PIVOTS) — skip the
                    # remaining escalations and go straight to the
                    # component fallback, saving their pivot-selection
                    # passes (measured ~2/5 of the 300k anchor's spill
                    # wall). Marginal overshoots keep escalating.
                    if screen_dup >= CONCENTRATION_CELL_FRAC * screen_m:
                        break
                    continue  # escalate without the full-node pass
            # chord distances to pivots in one pass (device when
            # resident: bands inflated by the bf16 slack, supersets of
            # the host copy-sets); f32 rounding is covered by the
            # caller's slack inside `halo`
            if dev_sub is not None:
                try:
                    with obs.span(
                        "spill.membership", node=int(len(idx))
                    ):
                        assign, member = faults.supervised(
                            faults.SITE_SPILL,
                            lambda _b: sdev.membership_device(
                                dev_sub, piv, halo
                            ),
                            label="membership",
                        )
                except Exception as e:  # noqa: BLE001
                    logger.warning(
                        "spill: device membership failed (%s); host", e
                    )
                    faults.note_degrade()
                    dev_root = dev_sub = None
                    sub = ops.take(idx)
            if dev_sub is None:
                with obs.span(
                    "spill.membership", node=int(len(idx)), host=True
                ):
                    assign, _d_min, _r, member = _membership(
                        _chords(sub, piv), halo
                    )
            sizes = member.sum(axis=0)
            if (
                float(sizes.sum()) / len(idx) <= MAX_DUP_FACTOR
                and int(sizes.max()) <= MAX_CHILD_FRAC * len(idx)
            ):
                split = (assign, member)
                break
        if degenerate:
            logger.warning(
                "spill: %d points sit inside one halo ball "
                "(all-duplicates regime); emitting an oversized leaf",
                len(idx),
            )
            leaves.append((idx, home))
            continue
        if split is None:
            # last resort before an oversized leaf: an exact-cover
            # component pre-split. Sparse retries the verified
            # prefix-filter at an ELEVATED pair budget (the cheap-budget
            # pass at the top bails on dense prefix indexes because the
            # pivot tree usually wins — but when the pivot tree itself
            # just failed, paying for verification is the only remaining
            # split). Dense runs leader-cover components — the same
            # concentration regime (cluster count >> pivot count, all
            # cross distances ~equal) with no sparse features to filter
            # on. Either way components are exact covers and enter the
            # stack as independent subtrees (no bands); a re-entered
            # oversized component either splits finer (progress) or
            # rediscovers itself (n_comp == 1 -> None -> oversized
            # leaf), so the recursion terminates.
            if isinstance(ops, _SparseOps):
                pc = prefix_components(
                    sub.x, 1.0 - halo * halo / 2.0,
                    budget=_PREFIX_RETRY_BUDGET,
                )
            elif dev_sub is not None:
                try:
                    with obs.span(
                        "spill.leader_cover", node=int(len(idx))
                    ):
                        pc = faults.supervised(
                            faults.SITE_SPILL,
                            lambda _b: sdev.leader_components_device(
                                dev_sub, halo, rng, _LEADER_EDGE_BUDGET
                            ),
                            label="leader-cover",
                        )
                except Exception as e:  # noqa: BLE001
                    logger.warning(
                        "spill: device leader cover failed (%s); host", e
                    )
                    faults.note_degrade()
                    dev_root = dev_sub = None
                    with obs.span(
                        "spill.leader_cover",
                        node=int(len(idx)),
                        host=True,
                    ):
                        pc = leader_components(
                            ops.take(idx), halo, rng
                        )
            else:
                with obs.span(
                    "spill.leader_cover", node=int(len(idx)), host=True
                ):
                    pc = leader_components(sub, halo, rng)
            if pc is not None and pc[1] > 1:
                # same bin-packing as the top-level pre-split: packed
                # bins become leaves on the next pop; oversized
                # components keep descending (their own retry is a
                # cheap 1-component rediscovery, the tolerable cost
                # of keeping subsets retryable — a pivot band can
                # drop bridge docs and make a child splittable even
                # when its parent was one verified component)
                packed, oversized = _component_bins(pc[0], pc[1], maxpp)
                for rows_b in packed:
                    stack.append((idx[rows_b], home[rows_b]))
                for rows_c in oversized:
                    stack.append((idx[rows_c], home[rows_c]))
                continue
            logger.warning(
                "spill: can't split %d points (every pivot set spills "
                ">%.1fx or one cell keeps >%.0f%%); emitting an "
                "oversized leaf",
                len(idx),
                MAX_DUP_FACTOR,
                100 * MAX_CHILD_FRAC,
            )
            leaves.append((idx, home))
            continue
        assign, member = split
        for c in range(member.shape[1]):
            sel = member[:, c]
            if not sel.any():
                continue
            stack.append((idx[sel], home[sel] & (assign[sel] == c)))

    n_parts = len(leaves)
    sizes = np.array([len(ix) for ix, _ in leaves], dtype=np.int64)
    part_ids = np.repeat(np.arange(n_parts, dtype=np.int64), sizes)
    point_idx = np.concatenate([ix for ix, _ in leaves])
    home_flat = np.concatenate([h for _, h in leaves])
    # sort instances by (partition, point index) — the packers' layout —
    # with one packed-key argsort (partition-major already holds, the
    # key just orders points within each leaf)
    order = np.argsort(part_ids * np.int64(n) + point_idx, kind="stable")
    point_idx = point_idx[order]
    home_flat = home_flat[order]
    home_of = np.full(n, -1, dtype=np.int32)
    home_of[point_idx[home_flat]] = part_ids[home_flat]
    if (home_of < 0).any():  # every point has exactly one home leaf
        raise AssertionError("spill: point with no home leaf")
    if info_out is not None:
        # the leaf layout downstream dispatchers consume directly
        # (instances are partition-major, so offsets = cumsum(counts))
        info_out["counts"] = sizes
    return part_ids, point_idx, n_parts, home_of
