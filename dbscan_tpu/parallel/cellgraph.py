"""Host connected-components over the fine-grid cell graph.

The banded engine's phase-1 sweep returns, per core point, a 25-bit mask
of window cells containing an eps-adjacent core (ops/banded.py). Because
every cell's cores form a clique (binning.FINE_CELL_FACTOR), cluster
connectivity collapses to the CELL graph: nodes are the globally-numbered
occupied cells (binning.CellGraphMeta), edges come from OR-ing the bitmasks
over each cell's points and expanding through the window-neighbor table.
Components — and the per-component seed, the minimum core fold index, which
reproduces the reference's sequential cluster numbering
(LocalDBSCANNaive.scala:45-64) — are solved here on the host in exact
integer arithmetic, replacing the device-side label-propagation iteration
entirely.

This pass is a distributed-DBSCAN analog of the reference's driver-side
graph work (DBSCANGraph.scala:70-87): tiny metadata, host-friendly, off the
accelerator's critical path.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from dbscan_tpu.ops.labels import SEED_NONE
from dbscan_tpu.parallel.binning import BANDED_WIN, BucketGroup, CellGraphMeta

_INF = np.iinfo(np.int64).max


def _connected_components(n: int, u: np.ndarray, v: np.ndarray) -> np.ndarray:
    """Component id per node of an undirected graph given edge arrays."""
    if len(u) == 0:
        return np.arange(n, dtype=np.int64)
    try:
        import scipy.sparse as sp
        from scipy.sparse.csgraph import connected_components

        g = sp.coo_matrix(
            (np.ones(len(u), dtype=np.int8), (u, v)), shape=(n, n)
        )
        return connected_components(g, directed=False)[1].astype(np.int64)
    except ImportError:
        # Vectorized min-label + pointer jumping; host gathers are fast
        # (unlike TPU), so this converges in O(log diameter) cheap rounds.
        comp = np.arange(n, dtype=np.int64)
        while True:
            nxt = comp.copy()
            np.minimum.at(nxt, u, comp[v])
            np.minimum.at(nxt, v, comp[u])
            nxt = nxt[nxt]
            if (nxt == comp).all():
                return comp
            comp = nxt


def compute_cell_labels(
    banded_results: Sequence[Tuple[BucketGroup, np.ndarray, np.ndarray]],
    meta: CellGraphMeta,
) -> List[np.ndarray]:
    """Labels for every banded group from its phase-1 outputs.

    banded_results: per banded group (group, core [P, B] bool, bits [P, B]
    int32) — phase-1 outputs pulled to host.
    meta: the CellGraphMeta from bucketize_banded.

    Returns one [P, B] int32 array per input group: at CORE positions the
    component seed (min core fold index over the cell component), SEED_NONE
    elsewhere — exactly the `labels` input of ops.banded.banded_phase2.
    """
    n_cells = meta.n_cells
    cell_fold_min = np.full(n_cells, _INF, dtype=np.int64)
    edges_u: List[np.ndarray] = []
    edges_v: List[np.ndarray] = []
    win_iota = np.arange(BANDED_WIN)

    for g, core, bits in banded_results:
        ext = g.banded
        flat_cg = ext.cell_gid.reshape(-1)
        valid = flat_cg >= 0
        cg = flat_cg[valid]
        if cg.size == 0:
            continue
        # cell runs are contiguous in the flattened row-major view (each
        # row is cell-sorted; a cell never spans rows/partitions)
        first = np.flatnonzero(np.r_[True, cg[1:] != cg[:-1]])
        ucell = cg[first]
        orbits = np.bitwise_or.reduceat(bits.reshape(-1)[valid], first)
        nzm = orbits != 0
        if nzm.any():
            src = ucell[nzm]
            unp = (orbits[nzm][:, None] >> win_iota) & 1
            ei, ej = np.nonzero(unp)
            edges_u.append(src[ei])
            # bits are only set where an adjacent core exists, so the
            # window cell is occupied: wintab hit guaranteed (>= 0)
            edges_v.append(meta.wintab[src[ei], ej].astype(np.int64))
        corev = core.reshape(-1)[valid]
        if corev.any():
            cgc = cg[corev]
            folds = ext.fold_idx.reshape(-1)[valid][corev].astype(np.int64)
            f2 = np.flatnonzero(np.r_[True, cgc[1:] != cgc[:-1]])
            # each cell lives in exactly one group: plain assignment
            cell_fold_min[cgc[f2]] = np.minimum.reduceat(folds, f2)

    u = np.concatenate(edges_u) if edges_u else np.empty(0, np.int64)
    v = np.concatenate(edges_v) if edges_v else np.empty(0, np.int64)
    comp = _connected_components(n_cells, u, v)

    # seed per component = min cell_fold_min over member cells (coreless
    # cells hold _INF and are never read back at a core position)
    seed_of_cell = np.full(n_cells, _INF, dtype=np.int64)
    if n_cells:
        order = np.argsort(comp, kind="stable")
        cs = comp[order]
        f3 = np.flatnonzero(np.r_[True, cs[1:] != cs[:-1]])
        compmin = np.minimum.reduceat(cell_fold_min[order], f3)
        seed_of_cell[order] = np.repeat(
            compmin, np.diff(np.r_[f3, n_cells])
        )

    out: List[np.ndarray] = []
    for g, core, bits in banded_results:
        ext = g.banded
        labels = np.full(ext.cell_gid.shape, SEED_NONE, dtype=np.int32)
        sel = core & (ext.cell_gid >= 0)
        labels[sel] = seed_of_cell[ext.cell_gid[sel]].astype(np.int32)
        out.append(labels)
    return out
