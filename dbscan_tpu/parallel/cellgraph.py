"""Host finalize for the banded engine: cell components + border algebra.

The banded engine's device sweeps return, per point, a core mask and a
25-bit mask of window cells containing an eps-adjacent core
(ops/banded.py). Because every cell's cores form a clique
(binning.FINE_CELL_FACTOR), everything after the distance work happens
here on the host, exactly and vectorized:

1. cluster connectivity collapses to the CELL graph — nodes are the
   globally-numbered occupied cells (binning.CellGraphMeta), edges come
   from OR-ing CORE rows' bitmasks over each cell and expanding through
   the window-neighbor table — solved with scipy/C connected components;
2. the per-component seed is the minimum core fold index, reproducing the
   reference's sequential cluster numbering (LocalDBSCANNaive.scala:45-64);
3. border/noise algebra (the dense engine's ``_finalize``, both reference
   engines' semantics): a non-core point's min adjacent-core seed is the
   min seed over its set bits — no third device sweep.

This pass is a distributed-DBSCAN analog of the reference's driver-side
graph work (DBSCANGraph.scala:70-87): tiny metadata, host-friendly, off
the accelerator's critical path.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from dbscan_tpu.ops.labels import BORDER, CORE, NOISE, NOT_FLAGGED, SEED_NONE
from dbscan_tpu.parallel.binning import BANDED_WIN, BucketGroup, CellGraphMeta

_INF = np.iinfo(np.int64).max


def _connected_components(n: int, u: np.ndarray, v: np.ndarray) -> np.ndarray:
    """Component id per node of an undirected graph given edge arrays."""
    if len(u) == 0:
        return np.arange(n, dtype=np.int64)
    try:
        import scipy.sparse as sp
        from scipy.sparse.csgraph import connected_components

        g = sp.coo_matrix(
            (np.ones(len(u), dtype=np.int8), (u, v)), shape=(n, n)
        )
        return connected_components(g, directed=False)[1].astype(np.int64)
    except ImportError:
        # Vectorized min-label + pointer jumping; host gathers are fast
        # (unlike TPU), so this converges in O(log diameter) cheap rounds.
        comp = np.arange(n, dtype=np.int64)
        while True:
            nxt = comp.copy()
            np.minimum.at(nxt, u, comp[v])
            np.minimum.at(nxt, v, comp[u])
            nxt = nxt[nxt]
            if (nxt == comp).all():
                return comp
            comp = nxt


def finalize_from_bits(
    banded_results: Sequence[Tuple[BucketGroup, np.ndarray, np.ndarray]],
    meta: CellGraphMeta,
    engine: str,
) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Seed labels + flags for every banded group from its device outputs.

    banded_results: per banded group (group, core [P, B] bool, bits [P, B]
    int32) — device sweep outputs pulled to host.
    meta: the CellGraphMeta from bucketize_banded.
    engine: "naive" | "archery" (border-adoption semantics, see
    ops/local_dbscan.py).

    Returns one (seed_labels [P, B] int32, flags [P, B] int8) pair per
    input group, in SORTED position order with fold-index label values —
    exactly what the device phase-2 sweep used to produce, bit-identical
    to the dense engine's output in f32.
    """
    if engine not in ("naive", "archery"):
        raise ValueError(f"unknown engine {engine!r}")
    n_cells = meta.n_cells
    cell_fold_min = np.full(n_cells, _INF, dtype=np.int64)
    edges_u: List[np.ndarray] = []
    edges_v: List[np.ndarray] = []
    win_iota = np.arange(BANDED_WIN)

    for g, core, bits in banded_results:
        ext = g.banded
        flat_cg = ext.cell_gid.reshape(-1)
        valid = flat_cg >= 0
        cg = flat_cg[valid]
        if cg.size == 0:
            continue
        # cell runs are contiguous in the flattened row-major view (each
        # row is cell-sorted; a cell never spans rows/partitions). Edges
        # come from CORE rows only — non-core rows' bits are border
        # candidates, not connectivity.
        corev = core.reshape(-1)[valid]
        ebits = np.where(corev, bits.reshape(-1)[valid], 0)
        first = np.flatnonzero(np.r_[True, cg[1:] != cg[:-1]])
        ucell = cg[first]
        orbits = np.bitwise_or.reduceat(ebits, first)
        nzm = orbits != 0
        if nzm.any():
            src = ucell[nzm]
            unp = (orbits[nzm][:, None] >> win_iota) & 1
            ei, ej = np.nonzero(unp)
            edges_u.append(src[ei])
            # bits are only set where an adjacent core exists, so the
            # window cell is occupied: wintab hit guaranteed (>= 0)
            edges_v.append(meta.wintab[src[ei], ej].astype(np.int64))
        if corev.any():
            cgc = cg[corev]
            folds = ext.fold_idx.reshape(-1)[valid][corev].astype(np.int64)
            f2 = np.flatnonzero(np.r_[True, cgc[1:] != cgc[:-1]])
            # each cell lives in exactly one group: plain assignment
            cell_fold_min[cgc[f2]] = np.minimum.reduceat(folds, f2)

    u = np.concatenate(edges_u) if edges_u else np.empty(0, np.int64)
    v = np.concatenate(edges_v) if edges_v else np.empty(0, np.int64)
    comp = _connected_components(n_cells, u, v)

    # seed per component = min cell_fold_min over member cells (coreless
    # cells hold _INF and are never read back at a core position)
    seed_of_cell = np.full(n_cells, _INF, dtype=np.int64)
    if n_cells:
        order = np.argsort(comp, kind="stable")
        cs = comp[order]
        f3 = np.flatnonzero(np.r_[True, cs[1:] != cs[:-1]])
        compmin = np.minimum.reduceat(cell_fold_min[order], f3)
        seed_of_cell[order] = np.repeat(
            compmin, np.diff(np.r_[f3, n_cells])
        )

    out: List[Tuple[np.ndarray, np.ndarray]] = []
    for g, core, bits in banded_results:
        ext = g.banded
        shape = ext.cell_gid.shape
        seeds = np.full(shape, SEED_NONE, dtype=np.int32)
        flags = np.full(shape, NOT_FLAGGED, dtype=np.int8)
        valid = ext.cell_gid >= 0
        flags[valid] = NOISE
        csel = core & valid
        seeds[csel] = seed_of_cell[ext.cell_gid[csel]].astype(np.int32)
        flags[csel] = CORE

        # border algebra (dense _finalize semantics): min adjacent-core
        # seed = min seed over the set bits' window cells
        nsel = valid & ~core & (bits != 0)
        if nsel.any():
            b = bits[nsel]
            unp = ((b[:, None] >> win_iota) & 1).astype(bool)
            wt = meta.wintab[ext.cell_gid[nsel]]  # [K, 25]
            cand = np.where(
                unp, seed_of_cell[np.maximum(wt, 0)], _INF
            )
            nbr_seed = cand.min(axis=1)  # < _INF: some bit is set
            if engine == "naive":
                # adopted only if the adopting expansion precedes the
                # point's own fold visit (LocalDBSCANNaive.scala:108-111)
                border = nbr_seed < ext.fold_idx[nsel]
            else:
                border = np.ones(len(nbr_seed), dtype=bool)
            rows = np.flatnonzero(nsel.reshape(-1))[border]
            seeds.reshape(-1)[rows] = nbr_seed[border].astype(np.int32)
            flags.reshape(-1)[rows] = BORDER
        out.append((seeds, flags))
    return out
