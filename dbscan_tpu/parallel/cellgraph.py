"""Host finalize for the banded engine: cell components + border algebra.

The banded engine's device sweeps return, per point, a core mask and a
25-bit mask of window cells containing an eps-adjacent core
(ops/banded.py). Because every cell's cores form a clique
(binning.FINE_CELL_FACTOR), everything after the distance work happens
here on the host, exactly and vectorized:

1. cluster connectivity collapses to the CELL graph — nodes are the
   globally-numbered occupied cells (binning.CellGraphMeta), edges come
   from OR-ing CORE rows' bitmasks over each cell and expanding through
   the window-neighbor table — solved with scipy/C connected components;
2. the per-component seed is the minimum core fold index, reproducing the
   reference's sequential cluster numbering (LocalDBSCANNaive.scala:45-64);
3. border/noise algebra (the dense engine's ``_finalize``, both reference
   engines' semantics): a non-core point's min adjacent-core seed is the
   min seed over its set bits — no third device sweep.

This pass is a distributed-DBSCAN analog of the reference's driver-side
graph work (DBSCANGraph.scala:70-87): tiny metadata, host-friendly, off
the accelerator's critical path.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from dbscan_tpu.ops.labels import BORDER, CORE, NOISE, NOT_FLAGGED, SEED_NONE
from dbscan_tpu.parallel.binning import BANDED_WIN, BucketGroup, CellGraphMeta

_INF = np.iinfo(np.int64).max


def _connected_components(n: int, u: np.ndarray, v: np.ndarray) -> np.ndarray:
    """Component id per node of an undirected graph given edge arrays."""
    if len(u) == 0:
        return np.arange(n, dtype=np.int64)
    try:
        import scipy.sparse as sp
        from scipy.sparse.csgraph import connected_components

        g = sp.coo_matrix(
            (np.ones(len(u), dtype=np.int8), (u, v)), shape=(n, n)
        )
        return connected_components(g, directed=False)[1].astype(np.int64)
    except ImportError:
        # Vectorized min-label + pointer jumping; host gathers are fast
        # (unlike TPU), so this converges in O(log diameter) cheap rounds.
        comp = np.arange(n, dtype=np.int64)
        while True:
            nxt = comp.copy()
            np.minimum.at(nxt, u, comp[v])
            np.minimum.at(nxt, v, comp[u])
            nxt = nxt[nxt]
            if (nxt == comp).all():
                return comp
            comp = nxt


def cell_layout(groups: Sequence[BucketGroup]) -> dict:
    """Flat-concat layout metadata for the compact-transfer path.

    Over the flat row-major concatenation of the given banded groups'
    [P, B] buffers, computes (all host-side, from the packer's cell ids):
    ``segflags`` per group ([P*B] bool, True where a new cell run starts —
    the device scan's segment resets), ``starts`` per group (positions of
    cell starts within the group's flat view, for min-reduceat), ``bases``
    per group (flat offset), and the per-cell OR readout plan: the device
    scan resets every SCAN_BLOCK slots, so a cell spanning blocks k0..k1
    needs its partial ORs gathered at each intervening block's last slot
    plus its own end slot — ``or_pos`` [G] flat gather positions grouped
    per cell, ``or_starts`` [U'] reduceat offsets into it, ``or_gid`` [U']
    the cell id per run. Cells are contiguous in the cell-sorted layout and
    never span rows, so run boundaries are exactly the id-change positions.
    """
    from dbscan_tpu.ops.banded import SCAN_BLOCK

    from dbscan_tpu import _native

    segflags, starts_l, bases, valid_l = [], [], [], []
    st_all, en_all, gid_all = [], [], []
    base = 0
    for g in groups:
        cg = g.banded.cell_gid.reshape(-1)
        m = cg.size
        native = _native.cell_runs(cg)
        if native is not None:
            flags, valid, st, en, gid = native
        else:
            prev = np.empty(m, dtype=np.int64)
            prev[0] = -2
            prev[1:] = cg[:-1]
            flags = cg != prev
            valid = cg >= 0
            st = np.flatnonzero(flags & valid)
            nxt = np.empty(m, dtype=np.int64)
            nxt[-1] = -2
            nxt[:-1] = cg[1:]
            en = np.flatnonzero(valid & (cg != nxt))
            gid = cg[en]
        segflags.append(flags)
        valid_l.append(valid)
        starts_l.append(st)
        st_all.append(st + base)
        en_all.append(en + base)
        gid_all.append(gid)
        bases.append(base)
        base += m
    if st_all:
        st_f = np.concatenate(st_all)
        en_f = np.concatenate(en_all)
        gid = np.concatenate(gid_all)
    else:
        st_f = en_f = gid = np.empty(0, np.int64)
    # per-cell gather runs: block ends of k0..k1-1, then the cell end
    nsp = en_f // SCAN_BLOCK - st_f // SCAN_BLOCK + 1
    or_starts = np.concatenate([[0], np.cumsum(nsp)])[:-1]
    total_g = int(nsp.sum())
    rel = np.arange(total_g, dtype=np.int64) - np.repeat(or_starts, nsp)
    or_pos = np.minimum(
        (np.repeat(st_f // SCAN_BLOCK, nsp) + rel + 1) * SCAN_BLOCK - 1,
        np.repeat(en_f, nsp),
    )
    return {
        "segflags": segflags,
        "starts": starts_l,
        "bases": bases,
        "total": base,
        "validflat": (
            np.concatenate(valid_l) if valid_l else np.empty(0, bool)
        ),
        "or_pos": or_pos,
        "or_starts": or_starts,
        "or_gid": gid,
    }


def unpack_combo(
    combo_host: np.ndarray, layout: dict
) -> Tuple[np.ndarray, np.ndarray]:
    """Host-oracle unpack of one pulled combo buffer: the unpacked core
    mask and the border-candidate positions (valid non-core slots).

    The ONE implementation shared by the driver's ``_pull_record`` and
    the tail-flush merge (their inlined copies had drifted in
    accounting flags) and by the device path's degrade-to-host
    fallback; ``combo_host[total // 8:]`` still carries the gathered
    scan bytes the caller views as int32.
    """
    total = layout["total"]
    core = np.unpackbits(combo_host[: total // 8], count=total).astype(bool)
    bpos = np.flatnonzero(layout["validflat"] & ~core)
    return core, bpos


def or_gid_positions(layout: dict) -> np.ndarray:
    """Per-GATHER-POSITION cell id for one chunk's OR readout plan:
    ``layout["or_gid"]`` names the cell per RUN of gather positions
    (``or_starts`` offsets); the device scatter-OR wants the cell per
    position. A cell spanning scan blocks repeats — OR is order-free."""
    n_pos = len(layout["or_pos"])
    runs = np.diff(np.r_[layout["or_starts"], n_pos])
    return np.repeat(layout["or_gid"], runs).astype(np.int32)


def device_chunk_arrays(
    groups: Sequence[BucketGroup], sentinel: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Flat per-slot (cell id, fold index) int32 arrays over one chunk's
    group concat — the device finalize's upload payload. Invalid slots
    (cell_gid < 0) carry ``sentinel`` (the padded cell table's last
    row), which doubles as the device-side validity test."""
    cells = np.concatenate(
        [g.banded.cell_gid.reshape(-1) for g in groups]
    )
    folds = np.concatenate(
        [g.banded.fold_idx.reshape(-1) for g in groups]
    ).astype(np.int32)
    return (
        np.where(cells < 0, np.int64(sentinel), cells).astype(np.int32),
        folds,
    )


def finalize_device(
    dev_chunks: Sequence[dict],
    wintab_dev,
    engine: str,
    out_slots: int,
    prop_mode: str = None,
):
    """Dispatch the fused device finalize (ops/banded.py
    ``compiled_cellcc_cc``) over the staged per-chunk device artifacts:
    cell CC (the shared min-label fixed point — iterated, or the
    single-pass union-find variant per ``DBSCAN_PROP_UNIONFIND``,
    ops/propagation.py ``window_cc``), component seeds, border algebra,
    and valid-prefix compaction — one ``cellcc.cc`` dispatch for the
    whole run, after one ``cellcc.unpack`` (or fused ``cellcc.fused``,
    ops/pallas_banded.py) per chunk folded the packed slabs into
    per-cell partials at flush time.

    ``dev_chunks``: per chunk, the dict staged by the driver —
    ``cellor``/``cellfold`` (unpack partials), ``core`` (unpacked core
    mask), ``cells``/``folds`` (uploaded flat metadata), ``bits`` (the
    resident phase-1 bitmasks), and optionally ``lab0`` (the fused
    path's first-sweep label partial — present on ALL chunks or used on
    none: a warm start from a partial first sweep would still converge
    to the same labels, but the counted sweeps would depend on the
    chunk mix). Returns the DEVICE handles ``(seeds [out_slots] int32,
    flags [out_slots] int8, iters)`` — the caller owns the pull
    (pipelined, supervised) and the per-group split
    (:func:`split_device_labels`); labels are byte-identical to
    :func:`finalize_compact` (see PARITY.md "Cellcc finalize" and
    "Propagation contract").
    """
    if engine not in ("naive", "archery"):
        raise ValueError(f"unknown engine {engine!r}")
    from dbscan_tpu.obs import compile as obs_compile
    from dbscan_tpu.ops.banded import compiled_cellcc_cc
    from dbscan_tpu.ops import propagation as prop_mod

    mode = prop_mod.prop_mode(prop_mode)
    warm = all("lab0" in c for c in dev_chunks) and bool(dev_chunks)
    labs = (
        tuple(c["lab0"] for c in dev_chunks) if warm else ()
    )
    return obs_compile.tracked_call(
        "cellcc.cc",
        compiled_cellcc_cc(engine, out_slots, mode, warm),
        wintab_dev,
        tuple(c["cellor"] for c in dev_chunks),
        tuple(c["cellfold"] for c in dev_chunks),
        tuple(c["core"] for c in dev_chunks),
        tuple(c["bits"] for c in dev_chunks),
        tuple(c["cells"] for c in dev_chunks),
        tuple(c["folds"] for c in dev_chunks),
        labs,
    )


def split_device_labels(
    seeds: np.ndarray, flags: np.ndarray, counts: Sequence[int]
) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Split the pulled compact label arrays back into the host
    finalize's per-group contract: one flat (seeds [cnt], flags [cnt])
    pair per group, valid slots in row-major prefix order — the device
    compaction preserves exactly that order, so this is pure slicing."""
    bounds = np.cumsum(np.asarray(counts, dtype=np.int64))
    out: List[Tuple[np.ndarray, np.ndarray]] = []
    lo = 0
    for hi in bounds:
        out.append((seeds[lo:hi], flags[lo:hi]))
        lo = int(hi)
    return out


def finalize_compact(
    groups: Sequence[BucketGroup],
    layout: dict,
    meta: CellGraphMeta,
    engine: str,
    core_flat: np.ndarray,
    or_vals: np.ndarray,
    border_pos: np.ndarray,
    border_bits: np.ndarray,
) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Seed labels + flags from the COMPACT device pulls (see
    ops/banded.py::banded_postpass) — the same label algebra as
    :func:`finalize_from_bits`, from M/8 + U + K transferred elements
    instead of 5 bytes per slot, returned FLAT: one (seeds [cnt], flags
    [cnt]) pair per group covering only the valid slots in row-major
    prefix order (the driver's instance order).

    core_flat: [M] bool unpacked core mask over the flat concat;
    or_vals: [G] int32 scan values gathered at ``layout["or_pos"]`` (the
    per-cell partial ORs, combined here via reduceat);
    border_pos/border_bits: flat positions and raw bitmasks of the valid
    non-core slots (the border candidates).
    """
    if engine not in ("naive", "archery"):
        raise ValueError(f"unknown engine {engine!r}")
    n_cells = meta.n_cells
    win_iota = np.arange(BANDED_WIN)

    cellor_by_gid = np.zeros(n_cells, dtype=np.int64)
    if len(or_vals):
        cellor_by_gid[layout["or_gid"]] = np.bitwise_or.reduceat(
            or_vals.astype(np.int64), layout["or_starts"]
        )

    # cell -> min core fold (the cluster seed value should that cell's
    # component win): min-reduceat over each group's flat folds, INF at
    # non-core slots; segments [start_i, start_{i+1}) may cross padding
    # slots, which hold INF and never win.
    cell_fold_min = np.full(n_cells, _INF, dtype=np.int64)
    for g, st, base in zip(groups, layout["starts"], layout["bases"]):
        if st.size == 0:
            continue
        cg = g.banded.cell_gid.reshape(-1)
        folds = np.where(
            core_flat[base : base + cg.size],
            g.banded.fold_idx.reshape(-1).astype(np.int64),
            _INF,
        )
        cell_fold_min[cg[st]] = np.minimum.reduceat(folds, st)

    # cell-graph edges from the per-cell OR masks (core rows only, by
    # construction of the device scan's input).
    src = np.flatnonzero(cellor_by_gid)
    if src.size:
        unp = (cellor_by_gid[src][:, None] >> win_iota) & 1
        ei, ej = np.nonzero(unp)
        u = src[ei]
        v = meta.wintab[u, ej].astype(np.int64)
    else:
        u = np.empty(0, np.int64)
        v = np.empty(0, np.int64)
    comp = _connected_components(n_cells, u, v)

    seed_of_cell = np.full(n_cells, _INF, dtype=np.int64)
    if n_cells:
        order = np.argsort(comp, kind="stable")
        cs = comp[order]
        f3 = np.flatnonzero(np.r_[True, cs[1:] != cs[:-1]])
        compmin = np.minimum.reduceat(cell_fold_min[order], f3)
        seed_of_cell[order] = np.repeat(compmin, np.diff(np.r_[f3, n_cells]))

    # border algebra on the candidate rows only (engine semantics as in
    # finalize_from_bits).
    bsel = border_bits != 0
    bpos = border_pos[bsel]
    bbits = border_bits[bsel]
    if bpos.size:
        # group of each candidate via the flat bases
        gidx = (
            np.searchsorted(
                np.asarray(layout["bases"] + [layout["total"]]), bpos, "right"
            )
            - 1
        )
        cg_b = np.empty(len(bpos), dtype=np.int64)
        fold_b = np.empty(len(bpos), dtype=np.int64)
        for i, (g, base) in enumerate(zip(groups, layout["bases"])):
            sel = gidx == i
            if not sel.any():
                continue
            loc = bpos[sel] - base
            cg_b[sel] = g.banded.cell_gid.reshape(-1)[loc]
            fold_b[sel] = g.banded.fold_idx.reshape(-1)[loc]
        unp = ((bbits[:, None] >> win_iota) & 1).astype(bool)
        wt = meta.wintab[cg_b]
        cand = np.where(unp, seed_of_cell[np.maximum(wt, 0)], _INF)
        nbr_seed = cand.min(axis=1)
        if engine == "naive":
            adopted = nbr_seed < fold_b
        else:
            adopted = np.ones(len(nbr_seed), dtype=bool)
        bpos = bpos[adopted]
        bseed = nbr_seed[adopted]
    else:
        bseed = np.empty(0, np.int64)

    # FLAT per-group outputs: seeds/flags over the VALID slots only, in
    # row-major prefix order — exactly the driver's instance order, so no
    # [P, B] materialization and no re-extraction downstream.
    out: List[Tuple[np.ndarray, np.ndarray]] = []
    for g, base in zip(groups, layout["bases"]):
        shape = g.banded.cell_gid.shape
        m = shape[0] * shape[1]
        cg = g.banded.cell_gid.reshape(-1)
        valid = cg >= 0
        cg_v = cg[valid]
        core_v = core_flat[base : base + m][valid]
        seeds = np.where(
            core_v, seed_of_cell[cg_v], np.int64(SEED_NONE)
        ).astype(np.int32)
        flags = np.where(core_v, CORE, NOISE).astype(np.int8)
        insel = (bpos >= base) & (bpos < base + m)
        if insel.any():
            # border candidates are valid non-core slots: map their flat
            # positions to valid-prefix ranks
            valid_rank = np.cumsum(valid) - 1
            loc = valid_rank[bpos[insel] - base]
            seeds[loc] = bseed[insel].astype(np.int32)
            flags[loc] = BORDER
        out.append((seeds, flags))
    return out


def finalize_from_bits(
    banded_results: Sequence[Tuple[BucketGroup, np.ndarray, np.ndarray]],
    meta: CellGraphMeta,
    engine: str,
) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Seed labels + flags for every banded group from its device outputs.

    banded_results: per banded group (group, core [P, B] bool, bits [P, B]
    int32) — device sweep outputs pulled to host.
    meta: the CellGraphMeta from bucketize_banded.
    engine: "naive" | "archery" (border-adoption semantics, see
    ops/local_dbscan.py).

    Returns one (seed_labels [P, B] int32, flags [P, B] int8) pair per
    input group, in SORTED position order with fold-index label values —
    exactly what the device phase-2 sweep used to produce, bit-identical
    to the dense engine's output in f32.
    """
    if engine not in ("naive", "archery"):
        raise ValueError(f"unknown engine {engine!r}")
    n_cells = meta.n_cells
    cell_fold_min = np.full(n_cells, _INF, dtype=np.int64)
    edges_u: List[np.ndarray] = []
    edges_v: List[np.ndarray] = []
    win_iota = np.arange(BANDED_WIN)

    for g, core, bits in banded_results:
        ext = g.banded
        flat_cg = ext.cell_gid.reshape(-1)
        valid = flat_cg >= 0
        cg = flat_cg[valid]
        if cg.size == 0:
            continue
        # cell runs are contiguous in the flattened row-major view (each
        # row is cell-sorted; a cell never spans rows/partitions). Edges
        # come from CORE rows only — non-core rows' bits are border
        # candidates, not connectivity.
        corev = core.reshape(-1)[valid]
        ebits = np.where(corev, bits.reshape(-1)[valid], 0)
        first = np.flatnonzero(np.r_[True, cg[1:] != cg[:-1]])
        ucell = cg[first]
        orbits = np.bitwise_or.reduceat(ebits, first)
        nzm = orbits != 0
        if nzm.any():
            src = ucell[nzm]
            unp = (orbits[nzm][:, None] >> win_iota) & 1
            ei, ej = np.nonzero(unp)
            edges_u.append(src[ei])
            # bits are only set where an adjacent core exists, so the
            # window cell is occupied: wintab hit guaranteed (>= 0)
            edges_v.append(meta.wintab[src[ei], ej].astype(np.int64))
        if corev.any():
            cgc = cg[corev]
            folds = ext.fold_idx.reshape(-1)[valid][corev].astype(np.int64)
            f2 = np.flatnonzero(np.r_[True, cgc[1:] != cgc[:-1]])
            # each cell lives in exactly one group: plain assignment
            cell_fold_min[cgc[f2]] = np.minimum.reduceat(folds, f2)

    u = np.concatenate(edges_u) if edges_u else np.empty(0, np.int64)
    v = np.concatenate(edges_v) if edges_v else np.empty(0, np.int64)
    comp = _connected_components(n_cells, u, v)

    # seed per component = min cell_fold_min over member cells (coreless
    # cells hold _INF and are never read back at a core position)
    seed_of_cell = np.full(n_cells, _INF, dtype=np.int64)
    if n_cells:
        order = np.argsort(comp, kind="stable")
        cs = comp[order]
        f3 = np.flatnonzero(np.r_[True, cs[1:] != cs[:-1]])
        compmin = np.minimum.reduceat(cell_fold_min[order], f3)
        seed_of_cell[order] = np.repeat(
            compmin, np.diff(np.r_[f3, n_cells])
        )

    out: List[Tuple[np.ndarray, np.ndarray]] = []
    for g, core, bits in banded_results:
        ext = g.banded
        shape = ext.cell_gid.shape
        seeds = np.full(shape, SEED_NONE, dtype=np.int32)
        flags = np.full(shape, NOT_FLAGGED, dtype=np.int8)
        valid = ext.cell_gid >= 0
        flags[valid] = NOISE
        csel = core & valid
        seeds[csel] = seed_of_cell[ext.cell_gid[csel]].astype(np.int32)
        flags[csel] = CORE

        # border algebra (dense _finalize semantics): min adjacent-core
        # seed = min seed over the set bits' window cells
        nsel = valid & ~core & (bits != 0)
        if nsel.any():
            b = bits[nsel]
            unp = ((b[:, None] >> win_iota) & 1).astype(bool)
            wt = meta.wintab[ext.cell_gid[nsel]]  # [K, 25]
            cand = np.where(
                unp, seed_of_cell[np.maximum(wt, 0)], _INF
            )
            nbr_seed = cand.min(axis=1)  # < _INF: some bit is set
            if engine == "naive":
                # adopted only if the adopting expansion precedes the
                # point's own fold visit (LocalDBSCANNaive.scala:108-111)
                border = nbr_seed < ext.fold_idx[nsel]
            else:
                border = np.ones(len(nbr_seed), dtype=bool)
            rows = np.flatnonzero(nsel.reshape(-1))[border]
            seeds.reshape(-1)[rows] = nbr_seed[border].astype(np.int32)
            flags.reshape(-1)[rows] = BORDER
        out.append((seeds, flags))
    return out
