"""Point-set I/O: CSV / Parquet / NumPy loaders and labeled-output writers.

The reference's only I/O is the sample driver's ``sc.textFile`` CSV parse and
``saveAsTextFile`` of ``"x,y,cluster"`` lines with hardcoded Windows paths
(DBSCANSample.scala:18-20,35). Here the same capability is a proper module:
format inferred from the extension (or forced), plain host-side readers
feeding the device pipeline, and writers that emit the reference's
``x,y,cluster`` shape plus a flag column.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

_CSV_EXTS = {".csv", ".txt", ".tsv"}
_PARQUET_EXTS = {".parquet", ".pq"}
_NUMPY_EXTS = {".npy", ".npz"}


def _infer_format(path: str, fmt: Optional[str]) -> str:
    if fmt:
        return fmt
    ext = os.path.splitext(path)[1].lower()
    if ext in _CSV_EXTS:
        return "csv"
    if ext in _PARQUET_EXTS:
        return "parquet"
    if ext in _NUMPY_EXTS:
        return "numpy"
    raise ValueError(
        f"cannot infer format from {path!r}; pass format= one of "
        "csv/parquet/numpy"
    )


def load_points(
    path: str, fmt: Optional[str] = None, delimiter: str = ","
) -> np.ndarray:
    """Load an [N, D>=2] float64 point array.

    csv: one point per line, ``delimiter``-separated floats (the reference
    sample's ``split(',').map(_.toDouble)``, DBSCANSample.scala:19-20).
    Extra columns ride along (the pipeline clusters on the first two,
    reference DBSCAN.scala:33-34).
    parquet: all numeric columns, in file order.
    numpy: .npy array or .npz (first array).
    """
    f = _infer_format(path, fmt)
    if f == "csv":
        pts = np.loadtxt(path, delimiter=delimiter, dtype=np.float64, ndmin=2)
    elif f == "parquet":
        import pyarrow.parquet as pq

        table = pq.read_table(path)
        cols = [
            np.asarray(table[name], dtype=np.float64)
            for name in table.column_names
            if np.issubdtype(np.asarray(table[name]).dtype, np.number)
        ]
        if not cols:
            raise ValueError(f"no numeric columns in {path!r}")
        pts = np.stack(cols, axis=1)
    elif f == "numpy":
        loaded = np.load(path)
        if isinstance(loaded, np.lib.npyio.NpzFile):
            loaded = loaded[loaded.files[0]]
        pts = np.asarray(loaded, dtype=np.float64)
    else:
        raise ValueError(f"unknown format {f!r}")
    if pts.ndim != 2 or pts.shape[1] < 2:
        raise ValueError(f"expected [N, >=2] points in {path!r}, got {pts.shape}")
    return pts


def save_labeled(
    path: str,
    points: np.ndarray,
    clusters: np.ndarray,
    flags: Optional[np.ndarray] = None,
    fmt: Optional[str] = None,
    delimiter: str = ",",
) -> None:
    """Write per-point results.

    csv: ``x,y,...,cluster[,flag]`` lines — the reference sample's
    ``"$x,$y,$cluster"`` output (DBSCANSample.scala:35) with the input's
    extra columns preserved and an optional flag code appended.
    parquet: columns ``c0..c{D-1}, cluster [, flag]``.
    numpy: .npz with ``points``, ``clusters`` [, ``flags``] arrays.
    """
    pts = np.asarray(points, dtype=np.float64)
    cl = np.asarray(clusters)
    f = _infer_format(path, fmt)
    if f == "csv":
        cols = [pts, cl[:, None].astype(np.int64)]
        if flags is not None:
            cols.append(np.asarray(flags)[:, None].astype(np.int64))
        widths = [pts.shape[1], 1] + ([1] if flags is not None else [])
        data = np.concatenate([np.asarray(c, dtype=np.float64) for c in cols], axis=1)
        fmt_spec = ["%.17g"] * pts.shape[1] + ["%d"] * (sum(widths) - pts.shape[1])
        np.savetxt(path, data, delimiter=delimiter, fmt=fmt_spec)
    elif f == "parquet":
        import pyarrow as pa
        import pyarrow.parquet as pq

        arrays = {f"c{i}": pts[:, i] for i in range(pts.shape[1])}
        arrays["cluster"] = cl.astype(np.int64)
        if flags is not None:
            arrays["flag"] = np.asarray(flags).astype(np.int64)
        pq.write_table(pa.table(arrays), path)
    elif f == "numpy":
        payload = {"points": pts, "clusters": cl}
        if flags is not None:
            payload["flags"] = np.asarray(flags)
        np.savez(path, **payload)
    else:
        raise ValueError(f"unknown format {f!r}")
