// Native host kernels for the tpu-dbscan driver's CPU-bound phases.
//
// The reference's host-side work runs on the JVM inside Spark's driver and
// executors (DBSCAN.scala:91-106, :179-285); ours runs in-process around the
// TPU dispatch. At 10M+ points the numpy formulation of these phases is
// multi-pass and allocation-heavy; the kernels here are single-pass, fused
// loops over the same data. Single-threaded by design: the deployment host
// for the driver is a 1-vCPU machine, so threads would only add overhead.
//
// Exposed via a tiny C ABI loaded with ctypes (dbscan_tpu/_native.py); every
// entry point has a numpy fallback, and outputs are bit-identical to the
// numpy path (asserted by tests/test_native.py).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <utility>
#include <vector>

namespace {

// Stable LSD radix argsort of NONNEGATIVE integer keys, 8-bit digits.
// All per-digit histograms are gathered in one pre-pass so passes whose
// digit is constant across the array (the common case for small key
// spaces in wide types) are skipped entirely. OrderT is int32 whenever
// n < 2^31 (the wrappers guarantee it) — half the ping-pong traffic.
template <typename K, typename OrderT>
void radix_argsort_impl(const K* keys, int64_t n, OrderT* order) {
  constexpr int NB = static_cast<int>(sizeof(K));
  if (n <= 0) return;
  std::vector<int64_t> hist(static_cast<size_t>(NB) * 256, 0);
  for (int64_t i = 0; i < n; ++i) {
    K k = keys[i];
    for (int b = 0; b < NB; ++b) {
      hist[static_cast<size_t>(b) * 256 + ((k >> (8 * b)) & 0xFF)]++;
    }
  }
  std::vector<K> kbuf1(keys, keys + n), kbuf2(n);
  std::vector<OrderT> obuf1(n), obuf2(n);
  for (int64_t i = 0; i < n; ++i) obuf1[i] = static_cast<OrderT>(i);
  K* ks = kbuf1.data();
  K* kd = kbuf2.data();
  OrderT* os = obuf1.data();
  OrderT* od = obuf2.data();
  for (int b = 0; b < NB; ++b) {
    int64_t* h = &hist[static_cast<size_t>(b) * 256];
    bool trivial = false;
    for (int v = 0; v < 256; ++v) {
      if (h[v] == n) {
        trivial = true;
        break;
      }
    }
    if (trivial) continue;
    int64_t offs[256];
    int64_t acc = 0;
    for (int v = 0; v < 256; ++v) {
      offs[v] = acc;
      acc += h[v];
    }
    const int sh = 8 * b;
    for (int64_t i = 0; i < n; ++i) {
      const int v = static_cast<int>((ks[i] >> sh) & 0xFF);
      const int64_t p = offs[v]++;
      kd[p] = ks[i];
      od[p] = os[i];
    }
    K* tk = ks;
    ks = kd;
    kd = tk;
    OrderT* to = os;
    os = od;
    od = to;
  }
  std::memcpy(order, os, static_cast<size_t>(n) * sizeof(OrderT));
}

// Fused group-by of nonnegative keys: stable sort order, dense rank per
// input element, unique keys and their counts — the native counterpart of
// ops/geometry.py::group_by_int_key (one sort + one linear pass instead of
// argsort / fancy-gather / diff / cumsum numpy round trips).
template <typename K, typename OrderT>
int64_t group_by_impl(const K* keys, int64_t n, OrderT* order,
                      OrderT* inverse, K* uniq, int64_t* counts) {
  if (n <= 0) return 0;
  radix_argsort_impl<K, OrderT>(keys, n, order);
  int64_t u = -1;
  K prev = 0;
  for (int64_t i = 0; i < n; ++i) {
    const K k = keys[order[i]];
    if (u < 0 || k != prev) {
      ++u;
      uniq[u] = k;
      counts[u] = 0;
      prev = k;
    }
    counts[u]++;
    inverse[order[i]] = static_cast<OrderT>(u);
  }
  return u + 1;
}

// band_dedup's sort + first-per-point sweep, templated on the argsort
// order type (int32 below 2^31 candidates — half the sort traffic).
template <typename OrderT>
int64_t band_dedup_sweep(const std::vector<int64_t>& keys, const int64_t* ci,
                         const int64_t* inst_pt, int64_t s,
                         int64_t* ck_out) {
  std::vector<OrderT> order(s);
  radix_argsort_impl<int64_t, OrderT>(keys.data(), s, order.data());
  int64_t m = 0;
  int64_t prev_pt = -1;
  for (int64_t j = 0; j < s; ++j) {
    const int64_t i = ci[order[j]];
    const int64_t pt = inst_pt[i];
    if (pt != prev_pt) {
      ck_out[m++] = i;
      prev_pt = pt;
    }
  }
  return m;
}

}  // namespace

extern "C" {

void radix_argsort_u32(const uint32_t* keys, int64_t n, int32_t* order) {
  radix_argsort_impl<uint32_t, int32_t>(keys, n, order);
}

void radix_argsort_u64(const uint64_t* keys, int64_t n, int32_t* order) {
  radix_argsort_impl<uint64_t, int32_t>(keys, n, order);
}

int64_t group_by_u32(const uint32_t* keys, int64_t n, int32_t* order,
                     int32_t* inverse, uint32_t* uniq, int64_t* counts) {
  return group_by_impl<uint32_t, int32_t>(keys, n, order, inverse, uniq,
                                          counts);
}

int64_t group_by_u64(const uint64_t* keys, int64_t n, int32_t* order,
                     int32_t* inverse, uint64_t* uniq, int64_t* counts) {
  return group_by_impl<uint64_t, int32_t>(keys, n, order, inverse, uniq,
                                          counts);
}

// Prefix-layout extraction helpers for the driver's instance tables
// (valid slots are the per-row prefix 0..count-1 in every packed group):
// (rows, slots) maps, count-repeated values, and prefix gathers from
// [P, B] buffers — each one sequential pass.
void prefix_maps(const int64_t* counts, int64_t p, int32_t* rows,
                 int32_t* slots) {
  int64_t o = 0;
  for (int64_t r = 0; r < p; ++r) {
    const int64_t c = counts[r];
    for (int64_t s = 0; s < c; ++s) {
      rows[o] = static_cast<int32_t>(r);
      slots[o] = static_cast<int32_t>(s);
      ++o;
    }
  }
}

void repeat_i64(const int64_t* vals, const int64_t* counts, int64_t p,
                int64_t* out) {
  int64_t o = 0;
  for (int64_t r = 0; r < p; ++r) {
    const int64_t v = vals[r];
    const int64_t c = counts[r];
    for (int64_t s = 0; s < c; ++s) out[o++] = v;
  }
}

void extract_prefix_i64(const int64_t* src, const int64_t* counts,
                        int64_t p, int64_t b, int64_t* out) {
  int64_t o = 0;
  for (int64_t r = 0; r < p; ++r) {
    const int64_t c = counts[r];
    std::memcpy(out + o, src + r * b, static_cast<size_t>(c) * 8);
    o += c;
  }
}

void extract_prefix_i32(const int32_t* src, const int64_t* counts,
                        int64_t p, int64_t b, int32_t* out) {
  int64_t o = 0;
  for (int64_t r = 0; r < p; ++r) {
    const int64_t c = counts[r];
    std::memcpy(out + o, src + r * b, static_cast<size_t>(c) * 4);
    o += c;
  }
}

void extract_prefix_i8(const int8_t* src, const int64_t* counts, int64_t p,
                       int64_t b, int8_t* out) {
  int64_t o = 0;
  for (int64_t r = 0; r < p; ++r) {
    const int64_t c = counts[r];
    std::memcpy(out + o, src + r * b, static_cast<size_t>(c));
    o += c;
  }
}

// Fused 2eps-grid key pass (ops/geometry.py::cell_histogram_int): snap
// both coordinates with the reference's negative-shift quirk
// (DBSCAN.scala:352-356), fold the index bounding box, and emit the
// row-major composite key — one pass instead of four [N]-wide numpy
// passes. Returns 0 and leaves key untouched if the span product would
// overflow the key space (caller falls back).
int64_t cell_keys(const double* pts, int64_t stride, int64_t n,
                  double cell_size, uint64_t* key, int64_t* bounds) {
  if (n <= 0) return 0;
  std::vector<int64_t> ix(n), iy(n);
  int64_t mnx = INT64_MAX, mny = INT64_MAX, mxx = INT64_MIN,
          mxy = INT64_MIN;
  for (int64_t i = 0; i < n; ++i) {
    double x = pts[stride * i];
    double y = pts[stride * i + 1];
    if (x < 0) x -= cell_size;
    if (y < 0) y -= cell_size;
    const int64_t cx = static_cast<int64_t>(std::trunc(x / cell_size));
    const int64_t cy = static_cast<int64_t>(std::trunc(y / cell_size));
    ix[i] = cx;
    iy[i] = cy;
    if (cx < mnx) mnx = cx;
    if (cy < mny) mny = cy;
    if (cx > mxx) mxx = cx;
    if (cy > mxy) mxy = cy;
  }
  const int64_t span_x = mxx - mnx + 1;
  const int64_t span_y = mxy - mny + 1;
  if (span_x > (int64_t(1) << 62) / span_y) return 0;
  for (int64_t i = 0; i < n; ++i) {
    key[i] = static_cast<uint64_t>((ix[i] - mnx) * span_y + (iy[i] - mny));
  }
  bounds[0] = mnx;
  bounds[1] = mny;
  bounds[2] = span_x;
  bounds[3] = span_y;
  return 1;
}

// Fused merge-band / inner-membership classification
// (parallel/driver.py::_classify_instances): one pass over the halo
// instance list replacing five [M]-wide numpy gathers plus the
// boundary-ring float tests (DBSCAN.scala:161-167, :304-315). A cell
// whose integer indices sit >= 1 inside the partition rect on every side
// is strictly interior to inner (cells are 2eps wide, inner = main
// shrunk by eps); only boundary-ring instances take the exact float
// containment tests.
void classify_instances(
    const double* pts,        // [N, D] row-major; first two columns used
    int64_t pts_stride,       // D (elements per row)
    const int64_t* cells,     // [C, 2] unique cell indices
    const int64_t* cell_inv,  // [N] cell row per point
    const int64_t* rects,     // [P, 4] integer partition rects
    const double* inner,      // [P, 4] float inner rects
    const double* main_r,     // [P, 4] float main rects
    const int64_t* inst_part, // [M]
    const int64_t* inst_pt,   // [M]
    int64_t m,
    uint8_t* band_any,        // [N] out (must be zeroed by caller)
    uint8_t* inst_inner       // [M] out
) {
  for (int64_t j = 0; j < m; ++j) {
    const int64_t p = inst_part[j];
    const int64_t i = inst_pt[j];
    const int64_t c = cell_inv[i];
    const int64_t ccx = cells[2 * c];
    const int64_t ccy = cells[2 * c + 1];
    const int64_t* r = rects + 4 * p;
    const bool interior = ccx >= r[0] + 1 && ccx <= r[2] - 2 &&
                          ccy >= r[1] + 1 && ccy <= r[3] - 2;
    if (interior) {
      inst_inner[j] = 1;
      continue;
    }
    const double px = pts[pts_stride * i];
    const double py = pts[pts_stride * i + 1];
    const double* in = inner + 4 * p;
    const bool inn =
        in[0] < px && px < in[2] && in[1] < py && py < in[3];
    inst_inner[j] = inn ? 1 : 0;
    if (!inn) {
      const double* mn = main_r + 4 * p;
      if (mn[0] <= px && px <= mn[2] && mn[1] <= py && py <= mn[3]) {
        band_any[i] = 1;
      }
    }
  }
}

// Fused fine-grid cell assignment for the banded packer
// (parallel/binning.py::bucketize_banded): per halo instance, cast the
// point to the device dtype (when f32 — cells must be computed from the
// coordinates the DEVICE sees), snap to the fine grid of the owning
// partition's outer rect, and fold per-partition cx/cy maxima — one pass
// replacing a gather + cast + four [M]-wide numpy passes + reduceat.
// cxmax/cymax must be zero-initialized by the caller.
void fine_cells(
    const double* pts,         // [N, D] row-major
    int64_t pts_stride,        // D
    const int64_t* point_idx,  // [M]
    const int64_t* part_ids,   // [M]
    const double* outer,       // [P, 4] grown rects
    double inv_cell,
    int64_t m,
    uint8_t is_f32,            // device dtype is float32
    int64_t* cx,               // [M] out
    int64_t* cy,               // [M] out
    int64_t* cxmax,            // [P] out (zeroed by caller)
    int64_t* cymax             // [P] out (zeroed by caller)
) {
  for (int64_t j = 0; j < m; ++j) {
    const int64_t pi = point_idx[j];
    const int64_t p = part_ids[j];
    double xd = pts[pts_stride * pi];
    double yd = pts[pts_stride * pi + 1];
    if (is_f32) {
      xd = static_cast<double>(static_cast<float>(xd));
      yd = static_cast<double>(static_cast<float>(yd));
    }
    double fx = std::floor((xd - outer[4 * p]) * inv_cell);
    double fy = std::floor((yd - outer[4 * p + 1]) * inv_cell);
    const int64_t cxi = fx > 0.0 ? static_cast<int64_t>(fx) : 0;
    const int64_t cyi = fy > 0.0 ? static_cast<int64_t>(fy) : 0;
    cx[j] = cxi;
    cy[j] = cyi;
    if (cxi > cxmax[p]) cxmax[p] = cxi;
    if (cyi > cymax[p]) cymax[p] = cyi;
  }
}

// Fused relabel passes (parallel/driver.py train_arrays steps 6-8): the
// per-instance global-id fill and the inner/band scatter into the
// per-point outputs, each one sequential sweep instead of a chain of
// boolean-mask gathers and fancy-indexed scatters.
void build_inst_gid(const uint8_t* labeled,   // [M]
                    const int32_t* urank,     // [L] ranks of labeled rows
                    const int64_t* gid_of_u,  // [K]
                    int64_t m, int32_t* gid   // [M] out
) {
  int64_t l = 0;
  for (int64_t j = 0; j < m; ++j) {
    gid[j] = labeled[j]
                 ? static_cast<int32_t>(gid_of_u[urank[l++]])
                 : 0;
  }
}

void scatter_sel(const int64_t* sel,       // [S] instance rows to apply
                 const int64_t* inst_pt,   // [M]
                 const int32_t* inst_gid,  // [M]
                 const int8_t* inst_flag,  // [M]
                 int64_t s,
                 int32_t* res_cluster,     // [N] out
                 int8_t* res_flag,         // [N] out
                 uint8_t* assigned         // [N] out
) {
  for (int64_t k = 0; k < s; ++k) {
    const int64_t j = sel[k];
    const int64_t pt = inst_pt[j];
    res_cluster[pt] = inst_gid[j];
    res_flag[pt] = inst_flag[j];
    assigned[pt] = 1;
  }
}

// Fused halo-candidate expansion (parallel/binning.py
// ::duplicate_points_grid): for each candidate (cell, foreign partition)
// pair, walk the cell's points (contiguous in the cell-sorted order) and
// keep those inside the partition's grown rectangle — one pass replacing
// the repeat/arange expansion plus the vectorized containment test.
// Returns the number of hits; out buffers need capacity sum(cell sizes
// over candidates).
int64_t halo_candidates(
    const int64_t* ccell,      // [K] candidate cell row
    const int64_t* cpart,      // [K] candidate partition id
    int64_t k,
    const int64_t* cstart,     // [C+1] cell -> sorted-point range
    const int32_t* order_pts,  // [N] cell-sorted point order
    const double* pts,         // [N, D]
    int64_t stride,
    const double* outer,       // [P, 4] grown rects
    int64_t* out_part, int64_t* out_pt) {
  int64_t o = 0;
  for (int64_t c = 0; c < k; ++c) {
    const int64_t cell = ccell[c];
    const int64_t p = cpart[c];
    const double* r = outer + 4 * p;
    for (int64_t s = cstart[cell]; s < cstart[cell + 1]; ++s) {
      const int64_t pt = order_pts[s];
      const double x = pts[stride * pt];
      const double y = pts[stride * pt + 1];
      if (r[0] <= x && x <= r[2] && r[1] <= y && y <= r[3]) {
        out_part[o] = p;
        out_pt[o] = pt;
        ++o;
      }
    }
  }
  return o;
}

// Fused cell-run extraction (parallel/cellgraph.py::cell_layout): one
// pass over a group's flat cell-id array yielding the device scan's
// segment-start flags, the validity mask, and the compacted (start, end,
// id) run table — cells are contiguous runs, padding is -1. Returns the
// number of runs; st/en/gid need capacity for m entries.
int64_t cell_runs(const int64_t* cg, int64_t m, uint8_t* segflags,
                  uint8_t* valid, int64_t* st, int64_t* en, int64_t* gid) {
  int64_t u = 0;
  int64_t prev = -2;
  for (int64_t i = 0; i < m; ++i) {
    const int64_t c = cg[i];
    const bool flag = c != prev;
    segflags[i] = flag ? 1 : 0;
    valid[i] = c >= 0 ? 1 : 0;
    if (flag) {
      if (prev >= 0) en[u - 1] = i - 1;
      if (c >= 0) {
        st[u] = i;
        gid[u] = c;
        ++u;
      }
    }
    prev = c;
  }
  if (prev >= 0) en[u - 1] = m - 1;
  return u;
}

// Fused band dedup (parallel/driver.py ::finalize_merge step 8): among
// the candidate instances `ci`, keep ONE per point — best flag first
// (Core=1 < Border=2 < Noise=3), then lowest partition id — via a stable
// radix argsort of the same packed key the numpy path builds,
// (pt * 4 + flag) * p_true + part, then a first-per-point sweep. One
// call replacing three 13M-element key temporaries, the argsort, and
// two fancy-indexed gathers. Writes the kept instance rows to ck_out
// (capacity s) and returns their count.
int64_t band_dedup(const int64_t* ci, int64_t s, const int64_t* inst_pt,
                   const int8_t* inst_flag, const int64_t* inst_part,
                   int64_t p_true, int64_t* ck_out) {
  if (s <= 0) return 0;
  std::vector<int64_t> keys(s);
  for (int64_t j = 0; j < s; ++j) {
    const int64_t i = ci[j];
    keys[j] = (inst_pt[i] * 4 + inst_flag[i]) * p_true + inst_part[i];
  }
  if (s < (int64_t{1} << 31)) {
    return band_dedup_sweep<int32_t>(keys, ci, inst_pt, s, ck_out);
  }
  return band_dedup_sweep<int64_t>(keys, ci, inst_pt, s, ck_out);
}

// Union-find + dense global-id assignment (parallel/driver.py
// ::finalize_merge step 7; reference DBSCAN.scala:206-222): union the
// rank-keyed cluster edge list, then walk the unique cluster table in
// its deterministic (part, loc)-sorted order assigning 1-based ids in
// first-appearance order of each component. Replaces the interpreted
// per-edge dict union-find plus the per-key assignment loop — the last
// O(edges + clusters) Python sections of the merge. Edge endpoints are
// DENSE RANKS into the unique table (the caller derives them from its
// numbering), so nodes are indexed directly. Returns the number of
// unique clusters, or -1 on an out-of-range endpoint (caller falls back
// to the Python path).
int64_t uf_assign_gids(const int64_t* edge_a,  // [E] node ranks
                       const int64_t* edge_b,  // [E]
                       int64_t n_edges,
                       int64_t n_nodes,
                       int64_t* gid_out        // [K] 1-based ids
) {
  std::vector<int64_t> parent(n_nodes), sz(n_nodes, 1);
  for (int64_t i = 0; i < n_nodes; ++i) parent[i] = i;
  auto find = [&](int64_t x) -> int64_t {
    int64_t root = x;
    while (parent[root] != root) root = parent[root];
    while (parent[x] != root) {
      const int64_t nx = parent[x];
      parent[x] = root;
      x = nx;
    }
    return root;
  };
  for (int64_t e = 0; e < n_edges; ++e) {
    const int64_t a = edge_a[e];
    const int64_t b = edge_b[e];
    if (a < 0 || a >= n_nodes || b < 0 || b >= n_nodes) return -1;
    int64_t ra = find(a);
    int64_t rb = find(b);
    if (ra == rb) continue;
    if (sz[ra] < sz[rb]) std::swap(ra, rb);
    parent[rb] = ra;
    sz[ra] += sz[rb];
  }
  // sz is dead past the union phase: reuse it as the root -> gid table
  // (0 = unseen) instead of a third allocation
  std::fill(sz.begin(), sz.end(), 0);
  int64_t next_id = 0;
  for (int64_t i = 0; i < n_nodes; ++i) {
    const int64_t r = find(i);
    if (sz[r] == 0) sz[r] = ++next_id;
    gid_out[i] = sz[r];
  }
  return next_id;
}

}  // extern "C"

namespace {

// Fused banded group packer (parallel/binning.py::bucketize_banded's
// per-group block): writes all eight [P_g, B, ...] device/host buffers in
// ONE sequential pass over the group's sorted instance ranges, with the
// sort indirection applied on the fly — replacing ~10 fancy-indexed numpy
// scatters (plus their np.full initializations) per group. Instances of
// partition p occupy sorted positions [part_start[p], part_start[p] +
// counts[p]) and slots 0..count-1 of row g, so padding is a pure suffix
// fill per row. Buffers may arrive uninitialized (np.empty). TS is the
// run-table element type — uint16 whenever the slab bound fits (halves
// the largest host-to-device upload; the device widens after transfer).
template <typename T, typename TS>
void pack_banded_group_impl(
    const int64_t* sel_parts,  // [G] original partition id per row
    int64_t n_sel, int64_t p_pad,
    const int64_t* part_start, // [P] first sorted position per partition
    const int64_t* counts,     // [P]
    const int64_t* order,      // [M] sort order (sorted pos -> instance)
    const double* pts,         // [N, D]
    int64_t pts_stride,
    const int64_t* point_idx,  // [M] instance -> original point row
    const int64_t* cx_s,       // [M] fine cx in SORTED order
    const int64_t* cell_rank,  // [M] global cell id in SORTED order
    const int32_t* ustarts,    // [U, 5] per-cell run starts
    const int32_t* uspans,     // [U, 5] per-cell run lengths
    const int32_t* sstart,     // [P * maxnb, 5] slab origins
    int64_t maxnb, int64_t tblock, int64_t b,
    int64_t d_out,             // payload columns copied into buf (2 for
                               // planar runs, 3 for spherical-chord runs)
    T* buf,                    // [p_pad, b, d_out] out
    uint8_t* mask,             // [p_pad, b] out
    int64_t* idx,              // [p_pad, b] out
    int32_t* fold_b,           // [p_pad, b] out
    TS* st_b,                  // [p_pad, b, 5] out
    TS* sp_b,                  // [p_pad, b, 5] out
    int32_t* cx_b,             // [p_pad, b] out
    int64_t* cgid_b            // [p_pad, b] out
) {
  for (int64_t g = 0; g < p_pad; ++g) {
    const int64_t p = g < n_sel ? sel_parts[g] : -1;
    const int64_t cnt = p >= 0 ? counts[p] : 0;
    const int64_t s0 = p >= 0 ? part_start[p] : 0;
    T* rbuf = buf + g * b * d_out;
    uint8_t* rmask = mask + g * b;
    int64_t* ridx = idx + g * b;
    int32_t* rfold = fold_b + g * b;
    TS* rst = st_b + g * b * 5;
    TS* rsp = sp_b + g * b * 5;
    int32_t* rcx = cx_b + g * b;
    int64_t* rcgid = cgid_b + g * b;
    for (int64_t s = 0; s < cnt; ++s) {
      const int64_t gi = s0 + s;            // sorted position
      const int64_t inst = order[gi];       // original instance row
      const int64_t pi = point_idx[inst];
      for (int64_t c = 0; c < d_out; ++c) {
        rbuf[d_out * s + c] = static_cast<T>(pts[pts_stride * pi + c]);
      }
      rmask[s] = 1;
      ridx[s] = pi;
      rfold[s] = static_cast<int32_t>(inst - s0);
      const int64_t cr = cell_rank[gi];
      const int32_t* ss = sstart + (p * maxnb + s / tblock) * 5;
      for (int k = 0; k < 5; ++k) {
        const int32_t sp = uspans[5 * cr + k];
        rsp[5 * s + k] = static_cast<TS>(sp);
        rst[5 * s + k] =
            static_cast<TS>(sp > 0 ? ustarts[5 * cr + k] - ss[k] : 0);
      }
      rcx[s] = static_cast<int32_t>(cx_s[gi]);
      rcgid[s] = cr;
    }
    for (int64_t s = cnt; s < b; ++s) {
      for (int64_t c = 0; c < d_out; ++c) {
        rbuf[d_out * s + c] = static_cast<T>(0);
      }
      rmask[s] = 0;
      ridx[s] = -1;
      rfold[s] = static_cast<int32_t>(s);
      for (int k = 0; k < 5; ++k) {
        rsp[5 * s + k] = 0;
        rst[5 * s + k] = 0;
      }
      rcx[s] = 0;
      rcgid[s] = -1;
    }
  }
}

}  // namespace

extern "C" {

#define DEFINE_PACK(SUFFIX, T, TS)                                          \
  void pack_banded_group_##SUFFIX(                                          \
      const int64_t* sel_parts, int64_t n_sel, int64_t p_pad,               \
      const int64_t* part_start, const int64_t* counts,                     \
      const int64_t* order, const double* pts, int64_t pts_stride,          \
      const int64_t* point_idx, const int64_t* cx_s,                        \
      const int64_t* cell_rank, const int32_t* ustarts,                     \
      const int32_t* uspans, const int32_t* sstart, int64_t maxnb,          \
      int64_t tblock, int64_t b, int64_t d_out, T* buf, uint8_t* mask,      \
      int64_t* idx, int32_t* fold_b, TS* st_b, TS* sp_b, int32_t* cx_b,     \
      int64_t* cgid_b) {                                                    \
    pack_banded_group_impl<T, TS>(                                          \
        sel_parts, n_sel, p_pad, part_start, counts, order, pts,            \
        pts_stride, point_idx, cx_s, cell_rank, ustarts, uspans, sstart,    \
        maxnb, tblock, b, d_out, buf, mask, idx, fold_b, st_b, sp_b,        \
        cx_b, cgid_b);                                                      \
  }

DEFINE_PACK(f32, float, int32_t)
DEFINE_PACK(f64, double, int32_t)
DEFINE_PACK(f32_u16, float, uint16_t)
DEFINE_PACK(f64_u16, double, uint16_t)

#undef DEFINE_PACK

}  // extern "C"
