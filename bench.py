"""Benchmark harness: distributed DBSCAN throughput on the local accelerator
vs a CPU baseline of the SAME pipeline (XLA-CPU), plus ARI cross-checks.

Prints exactly ONE JSON line:
  {"metric": ..., "value": <Mpoints/s on accelerator>, "unit": "Mpoints/s",
   "vs_baseline": <accelerator/cpu speedup>, ...extras}

The reference publishes no numbers (BASELINE.md); the baseline here is the
same workload on XLA-CPU in a subprocess — a strictly stronger baseline than
Spark-CPU's scalar JVM loops for this O(B^2)-per-partition algorithm (see
BASELINE.md "honest-comparison note" for why, and why extrapolating its 100k
rate overstates it).

Correctness in the line itself:
- ari_vs_cpu: accelerator vs XLA-CPU labels on the cpu_n-point subset;
- ari_full: the TIMED full-N accelerator run's labels vs an independent
  second full-N run at a different partitioning (maxpp/2 — different
  bucket widths, halo routes, and merge order must reproduce the labels).

Env knobs: BENCH_N (points, default 1M), BENCH_MAXPP (max points per
partition on the accelerator, default 262144 — large partitions route the
fine-grid banded engine and amortize the halo duplication and host merge;
measured fastest at 1M on v5e), BENCH_CPU_MAXPP (baseline partition size,
default 2048 — the CPU's own sweet spot; the quadratic per-partition cost
favors smaller partitions there), BENCH_CPU_N (baseline points, default
min(N, 100k)), BENCH_PALLAS (1 = route the accelerator run through the
streaming Pallas kernels; the CPU baseline always uses the XLA path),
BENCH_ANCHOR / BENCH_HAVERSINE / BENCH_COSINE (default ON; "0" disables —
the engineered-structure rows: exact expected cluster count + ARI vs
construction for euclidean / haversine / 512-d-embedding cosine via spill
partitioning; BENCH_ANCHOR_N / BENCH_HAV_N / BENCH_COS_N resize, defaults
10M / 10M / 1M on the accelerator and 200k / 100k / 50k on the CPU
fallback), BENCH_BUDGET_S (wall budget for the extra rows, default 1500 s;
rows past it emit "<row>_skipped": "time_budget" instead of running).

`bench.py --embed` is the standalone embed-engine capture
(dbscan_tpu/embed): exact-path throughput (`embed_mpts`, gated
regress-down) plus the subsampled-edge accuracy contract (`embed_ari`
= sampled vs exact labels at BENCH_EMBED_SAMPLE_FRAC, gated
regress-down against the declared floor — PARITY.md "Embed accuracy
contract"). Knobs: BENCH_EMBED_{N,D,MAXPP,SAMPLE_FRAC,REPS}.

`bench.py --hdbscan` is the standalone density-engine capture
(dbscan_tpu/density): multi-density anchor (geomspaced blob scales —
the workload plain DBSCAN's single eps cannot separate) recovered by
hdbscan(), with throughput (`hdbscan_mpts`, gated regress-down),
device-vs-construction ARI (`hdbscan_construction_ari`, gated
regress-down), and the Borůvka contraction depth
(`hdbscan_boruvka_rounds`, unit "rounds", gated regress-UP like
`_spill_levels` — PARITY.md "Variable-density contract"). Knobs:
BENCH_HDBSCAN_{N,MIN_PTS,REPS}.
"""

import hashlib
import inspect
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

EPS = 0.35
MIN_POINTS = 10


def make_data(n: int) -> np.ndarray:
    """Clustered + noise workload (moons/blobs-style per BASELINE.json
    configs[0]), spread over a wide area so spatial partitioning engages."""
    rng = np.random.default_rng(42)
    n_clusters = max(4, n // 25000)
    centers = rng.uniform(-60, 60, size=(n_clusters, 2))
    per = (n * 9 // 10) // n_clusters
    pts = np.concatenate(
        [rng.normal(c, 0.8, size=(per, 2)) for c in centers]
        + [rng.uniform(-70, 70, size=(n - per * n_clusters, 2))]
    ).astype(np.float64)
    rng.shuffle(pts)
    return pts


def make_anchor(n: int, kind: str):
    """Engineered separated-cluster workload: K hotspots with known
    membership (the >=10M correctness anchor, VERDICT r1 item 5). Returns
    (points, blob_of [n_blob], n_blob, K, eps). Separation/spread are set
    so every blob is one cluster and blobs never bridge: spacing >= 10x
    eps, sigma ~ 0.3x eps; K scales with N so per-blob counts stay far
    above minPts (~5000/blob at the 10M reference size). ``kind`` is
    euclidean / haversine / cosine (cosine: 512-d unit-sphere blobs,
    random-direction noise — sim ~0 to everything)."""
    rng = np.random.default_rng(42)
    if kind == "cosine":
        d = 512
        k = min(1000, max(16, n // 1000))
        n_noise = n // 1000
        n_blob = n - n_noise
        blob_of = rng.integers(0, k, n_blob)
        centers = rng.normal(size=(k, d)).astype(np.float32)
        centers /= np.linalg.norm(centers, axis=1, keepdims=True)
        # generate noise straight in f32: an f64 temporary would be
        # ~41 GB at the 10M resize (the same copy the driver avoids)
        pts = rng.standard_normal((n, d), dtype=np.float32)
        pts[:n_blob] *= np.float32(0.002)
        pts[:n_blob] += centers[blob_of]
        return pts, blob_of, n_blob, k, 0.02
    k = min(2000, max(16, n // 2500))
    gx = int(np.ceil(np.sqrt(k)))
    n_noise = n // 1000
    n_blob = n - n_noise
    blob_of = rng.integers(0, k, n_blob)
    pts = np.empty((n, 2))
    if kind == "haversine":
        km_lat = 111.0
        km_lon = 111.0 * np.cos(np.deg2rad(40.75))
        centers = np.stack(
            np.meshgrid(
                -74.3 + (np.arange(gx) + 0.5) * 1.1 / km_lon,
                40.5 + (np.arange(gx) + 0.5) * 1.1 / km_lat,
            ),
            -1,
        ).reshape(-1, 2)[:k]
        pts[:n_blob, 0] = centers[blob_of, 0] + rng.normal(
            0, 0.030 / km_lon, n_blob
        )
        pts[:n_blob, 1] = centers[blob_of, 1] + rng.normal(
            0, 0.030 / km_lat, n_blob
        )
        pts[n_blob:, 0] = rng.uniform(-74.3, -73.7, n_noise)
        pts[n_blob:, 1] = rng.uniform(40.5, 41.0, n_noise)
        eps = 0.1  # km
    else:
        centers = np.stack(
            np.meshgrid(np.arange(gx) * 4.0, np.arange(gx) * 4.0), -1
        ).reshape(-1, 2)[:k]
        pts[:n_blob] = centers[blob_of] + rng.normal(0, 0.1, (n_blob, 2))
        pts[n_blob:] = rng.uniform(-2, gx * 4.0, (n_noise, 2))
        eps = EPS
    return pts, blob_of, n_blob, k, eps


# Anchor-generator version, part of make_anchor_cached's key. The key
# already embeds a hash of make_anchor's OWN source, but that hash is
# blind to edits outside the function body — a helper it starts calling,
# a module constant it reads (EPS), a numpy RNG behavior change after an
# upgrade. Bump this alongside ANY generator-affecting change the source
# hash cannot see, so a budgeted campaign can never be handed a stale
# workload from before the edit (ADVICE r5 low).
ANCHOR_GENERATOR_VERSION = "1"


def make_anchor_cached(n: int, kind: str):
    """make_anchor with an on-disk cache (the arrays are seed-
    deterministic, so the cache is pure). The 100M campaign regenerates
    the SAME 1.6 GB anchor at the top of every retry leg — minutes of
    RNG that the tunneled worker's ~4-25-min endurance window cannot
    spare; a cached leg loads in seconds and spends the window on
    device work instead. Opt out with BENCH_ANCHOR_CACHE= (empty)."""
    cache_root = os.environ.get("BENCH_ANCHOR_CACHE", "/tmp/anchor_cache")
    if not cache_root:
        return make_anchor(n, kind)
    # self-enforcing invalidation: the key embeds a hash of
    # make_anchor's SOURCE, so any generator change (eps/sigma/spacing/
    # k formulas, RNG stream order) re-keys the cache automatically —
    # a stale hit would hand a budgeted campaign the wrong workload
    # with no warning
    src_h = hashlib.sha1(
        inspect.getsource(make_anchor).encode()
    ).hexdigest()[:10]
    base = os.path.join(
        cache_root, f"{kind}_{n}_v{ANCHOR_GENERATOR_VERSION}_{src_h}"
    )
    meta_p, pts_p, blob_p = (
        base + "_meta.npz",
        base + "_pts.npy",
        base + "_blob.npy",
    )
    try:
        with np.load(meta_p) as meta:
            n_blob = int(meta["n_blob"])
            k = int(meta["k"])
            eps = float(meta["eps"])
        pts = np.load(pts_p)
        blob_of = np.load(blob_p)
        if len(pts) == n:
            return pts, blob_of, n_blob, k, eps
    except Exception:  # noqa: BLE001 — ANY unreadable/torn cache entry
        # (incl. zipfile.BadZipFile from a truncated meta) must fall
        # through to regeneration, never wedge the retry legs
        pass
    pts, blob_of, n_blob, k, eps = make_anchor(n, kind)
    try:  # best-effort save; atomic per file so a killed leg can't
        # leave a torn cache (meta written LAST — readers key on it)
        os.makedirs(cache_root, exist_ok=True)
        for path, arr in ((pts_p, pts), (blob_p, blob_of)):
            np.save(path + ".tmp.npy", arr)
            os.replace(path + ".tmp.npy", path)
        with open(meta_p + ".tmp", "wb") as f:
            np.savez(f, n_blob=n_blob, k=k, eps=eps)
        os.replace(meta_p + ".tmp", meta_p)
    except OSError:
        pass
    return pts, blob_of, n_blob, k, eps


def make_sparse_anchor(n: int, vocab: int = 50_000, nnz: int = 60):
    """Engineered sparse TF-IDF-like workload (BASELINE.json configs[3]):
    k topic patterns of ~nnz weighted features, one per doc with
    multiplicative jitter — known memberships, high intra-topic cosine,
    ~orthogonal across topics. Built directly from COO arrays
    (sp.random is ~100x slower at this size)."""
    import scipy.sparse as sp

    rng = np.random.default_rng(42)
    k = max(16, n // 500)
    feat = rng.integers(0, vocab, size=(k, nnz))
    val = rng.random((k, nnz)) + 0.1
    blob_of = rng.integers(0, k, n)
    rows = np.repeat(np.arange(n), nnz)
    cols = feat[blob_of].ravel()
    vals = (val[blob_of] * rng.uniform(0.9, 1.1, (n, nnz))).ravel()
    x = sp.coo_matrix((vals, (rows, cols)), shape=(n, vocab)).tocsr()
    return x, blob_of, k


def sparse_row(prefix: str, n: int, maxpp: int) -> dict:
    """Engineered sparse-cosine run (the TF-IDF config): exact expected
    cluster count + construction ARI + throughput, same warm-up/best-of
    discipline as anchor_row."""
    from dbscan_tpu.ops.sparse import sparse_cosine_dbscan
    from dbscan_tpu.utils.ari import adjusted_rand_index

    x, blob_of, k = make_sparse_anchor(n)
    kw = dict(eps=0.05, min_points=5, max_points_per_partition=maxpp)
    stats: dict = {}
    # warm-up on a SUBSET: leaf shapes are maxpp-bounded ladder rungs,
    # identical at any n, so a 20k-doc run compiles the same kernel
    # family for ~5% of a full-size warm-up's wall (the full-size warm-up
    # was the single largest cost of the r3 captures' budget)
    sparse_cosine_dbscan(x[: min(n, 20_000)], **kw)
    reps = int(os.environ.get("BENCH_SPARSE_REPS", "1"))
    dt = float("inf")
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        rep_stats: dict = {}
        clusters, _flags = sparse_cosine_dbscan(x, stats_out=rep_stats, **kw)
        dt_rep = time.perf_counter() - t0
        if dt_rep < dt:  # phase split of the hot run being reported
            dt, stats = dt_rep, rep_stats
    ari = adjusted_rand_index(clusters, blob_of)
    out = {
        f"{prefix}_n": n,
        f"{prefix}_seconds": round(dt, 2),
        f"{prefix}_clusters": int(len(np.unique(clusters[clusters > 0]))),
        f"{prefix}_expect": k,
        f"{prefix}_ari": round(float(ari), 6),
        f"{prefix}_leaves": stats.get("n_partitions"),
        f"{prefix}_dup": stats.get("duplication_factor"),
        f"{prefix}_phases": _phases(stats),
        # the ROADMAP-item-2 figures, flat so the history ingests and
        # the regress gate trends them (walls regress up): the spill
        # wall of the hot rep + the level-build round count (0 = host
        # recursion)
        **_spill_fields(prefix, stats),
    }
    cpu_n = int(os.environ.get("BENCH_SPARSE_CPU_N", "30000"))
    out.update(_row_cpu_baseline(prefix, "sparse", cpu_n, n / dt))
    return out


# Single-chip TPU v5e MXU peak (bf16). The banded sweeps are f32
# VECTOR work (difference-form distances on the VPU), so their MFU
# against the matrix-unit peak is structurally small — the figure
# grounds the throughput claim in hardware terms and shows whether the
# kernel or the host is the ceiling, not that the MXU is saturated.
V5E_BF16_PEAK = 197e12
# Operative ceilings for the banded sweep (VERDICT r4 item 6): the sweep
# is VPU elementwise work streaming [5, S, D] slabs from HBM — the MXU
# peak above is NOT its roof. v5e public specs: 819 GB/s HBM BW; VPU f32
# issue ~ 8x128 lanes x 4 ALUs x ~0.94 GHz x 1 FLOP = ~3.9 TFLOP/s.
V5E_HBM_BYTES_S = 819e9
V5E_VPU_F32_PEAK = 3.9e12


def _spill_fields(prefix: str, stats: dict) -> dict:
    """Flat spill-tree figures for a cosine/sparse row: the spill wall
    (promotable `_s` key, regress-up) and the level-synchronous build's
    round count. Empty when the run never spilled."""
    t = dict(stats.get("timings") or {})
    if t.get("spill_partition_s") is None:
        return {}  # the run never spilled (grid metrics)
    out = {
        f"{prefix}_spill_partition_s": round(
            float(t["spill_partition_s"]), 3
        )
    }
    # stamped only when the level build actually ran: 0 means the host
    # recursion (CPU bench, or a degraded device build) — mixing those
    # into the gated history would make a silent degrade read as the
    # best possible depth and flag the next healthy capture
    if stats.get("spill_levels"):
        out[f"{prefix}_spill_levels"] = int(stats["spill_levels"])
    return out


def _cellcc_fields(prefix: str, stats: dict) -> dict:
    """Flat cellcc-finalize figures for a banded row: the whole-finalize
    wall (promotable `_s` key, regress-up) and — when the device
    finalize ran — its CC sweep count, so the history gate catches
    propagation-count blowups, not just wall regressions. Empty when
    the run had no banded finalize (dense/cosine paths)."""
    t = dict(stats.get("timings") or {})
    if t.get("cellcc_finalize_s") is None:
        return {}
    out = {
        f"{prefix}_cellcc_finalize_s": round(
            float(t["cellcc_finalize_s"]), 3
        )
    }
    # 0 means the host oracle ran (DBSCAN_CELLCC_DEVICE=0, a structural
    # exclusion, or a fault degrade) — mixing those into the gated
    # history would make a silent degrade read as the best possible
    # sweep count and flag the next healthy capture
    if stats.get("cellcc_cc_iters"):
        out[f"{prefix}_cellcc_cc_iters"] = int(stats["cellcc_cc_iters"])
    # shared-propagation figures (ops/propagation.py): the window_cc
    # sweep count rides next to _cc_iters (same 0-means-host rule) and
    # regresses UP in obs/regress; the resolved mode labels the row so
    # a sweep-count shift is attributable to the knob, not noise
    if stats.get("prop_sweeps"):
        out[f"{prefix}_prop_sweeps"] = int(stats["prop_sweeps"])
    if stats.get("prop_mode"):
        out[f"{prefix}_prop_mode"] = str(stats["prop_mode"])
    return out


def _phases(stats, top=8) -> dict:
    """Condense stats['timings'] to the `top` largest phases + total."""
    t = dict(stats.get("timings") or {})
    total = t.pop("total_s", 0.0)
    keys = sorted((k for k in t if t[k] > 0), key=lambda k: -t[k])[:top]
    out = {k: round(t[k], 2) for k in keys}
    out["total_s"] = round(total, 2)
    return out


def _mfu_fields(prefix: str, pts, maxpp: int, **extra) -> dict:
    """One instrumented hot run (DBSCAN_TIME_DEVICE=1: synchronous banded
    dispatch, no pack/compute overlap — never the timed run) isolating the
    device sweep window; reports the counted sweep-FLOP rate vs chip peak
    (VERDICT r3 item 3). Empty when the run had no banded groups."""
    from dbscan_tpu import Engine, train

    kw = dict(
        eps=EPS,
        min_points=MIN_POINTS,
        max_points_per_partition=maxpp,
        engine=Engine.ARCHERY,
    )
    kw.update(extra)
    prev_td = os.environ.get("DBSCAN_TIME_DEVICE")
    os.environ["DBSCAN_TIME_DEVICE"] = "1"
    try:
        model = train(pts, **kw)
    finally:
        if prev_td is None:
            os.environ.pop("DBSCAN_TIME_DEVICE", None)
        else:
            os.environ["DBSCAN_TIME_DEVICE"] = prev_td
    sync = model.stats["timings"].get("banded_p1_sync_s")
    flops = model.stats.get("banded_sweep_flops")
    if not sync or not flops:
        return {}
    rate = flops / sync
    out = {
        f"{prefix}_sweep_flops": int(flops),
        f"{prefix}_device_sweep_s": round(sync, 3),
        f"{prefix}_sweep_tflops": round(rate / 1e12, 3),
        f"{prefix}_mfu_vs_bf16_peak": round(rate / V5E_BF16_PEAK, 5),
    }
    nbytes = model.stats.get("banded_sweep_bytes")
    if nbytes:
        # roofline vs the OPERATIVE ceilings: counted slab-read traffic
        # against HBM bandwidth, and counted f32 sweep arithmetic
        # against VPU issue — whichever fraction is higher is the
        # binding resource (the MXU-relative number above is context,
        # not a target: no matmul is involved)
        bw = nbytes / sync
        frac_hbm = bw / V5E_HBM_BYTES_S
        frac_vpu = rate / V5E_VPU_F32_PEAK
        out.update(
            {
                f"{prefix}_sweep_bytes": int(nbytes),
                f"{prefix}_hbm_gbps": round(bw / 1e9, 1),
                f"{prefix}_roofline_vs_hbm": round(frac_hbm, 4),
                f"{prefix}_roofline_vs_vpu_f32": round(frac_vpu, 4),
                f"{prefix}_roofline_bound": (
                    "hbm" if frac_hbm >= frac_vpu else "vpu"
                ),
                f"{prefix}_roofline": round(max(frac_hbm, frac_vpu), 4),
            }
        )
    return out


def _row_cpu_baseline(prefix: str, kind: str, cpu_n: int, row_rate: float) -> dict:
    """XLA-CPU subprocess baseline for a cosine/sparse row (the euclid
    headline's `cpu_baseline_mpts` pattern, VERDICT r3 item 2a): same
    workload generator, same pipeline, CPU backend, at `cpu_n` points —
    the rate comparison extrapolates exactly as BASELINE.md's
    honest-comparison note documents."""
    import jax

    if jax.default_backend() == "cpu":
        return {}  # the row itself IS a CPU measurement
    if os.environ.get("BENCH_ROW_BASELINES", "1") == "0":
        return {}
    child = {"cosine": "--cos-child", "sparse": "--sparse-child"}[kind]
    # the child runs on the host CPU, but its wall still counts against
    # the capture's budget — cap it at a fraction of BENCH_BUDGET_S so a
    # slow baseline cannot starve the rows that follow
    budget = float(os.environ.get("BENCH_BUDGET_S", "1500"))
    timeout_s = int(
        os.environ.get(
            "BENCH_ROW_BASELINE_TIMEOUT_S", str(int(min(1800, 0.4 * budget)))
        )
    )
    with tempfile.TemporaryDirectory() as tmp:
        out_path = os.path.join(tmp, "out.npz")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        try:
            proc = subprocess.run(
                [
                    sys.executable, os.path.abspath(__file__), child,
                    str(cpu_n), out_path,
                ],
                env=env,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE,
                timeout=timeout_s,
            )
        except subprocess.TimeoutExpired:
            # the accelerator row is already measured — a hung baseline
            # must degrade THIS comparison, not discard the row
            return {f"{prefix}_baseline_failed": f"timeout>{timeout_s}s"}
        if proc.returncode != 0:
            sys.stderr.write(proc.stderr.decode(errors="replace")[-2000:])
            return {f"{prefix}_baseline_failed": int(proc.returncode)}
        res = np.load(out_path)
    cpu_rate = float(res["n"]) / float(res["seconds"])
    return {
        f"{prefix}_cpu_baseline_n": int(res["n"]),
        f"{prefix}_cpu_baseline_mpts": round(cpu_rate / 1e6, 5),
        f"{prefix}_vs_baseline": round(row_rate / max(cpu_rate, 1e-12), 3),
    }


def child_cos_cpu(cpu_n: int, out_path: str) -> None:
    """CPU-backend cosine baseline child: same generator/config as the
    cosine anchor row, warm-up + one timed run."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from dbscan_tpu import train

    pts, _blob_of, _n_blob, _k, eps = make_anchor(cpu_n, "cosine")
    maxpp = int(os.environ.get("BENCH_COS_MAXPP", "8192"))
    kw = dict(
        eps=eps, min_points=MIN_POINTS, metric="cosine",
        max_points_per_partition=maxpp,
    )
    train(pts, **kw)
    t0 = time.perf_counter()
    train(pts, **kw)
    np.savez(out_path, seconds=time.perf_counter() - t0, n=cpu_n)


def child_sparse_cpu(cpu_n: int, out_path: str) -> None:
    """CPU-backend sparse baseline child: same generator/config as the
    sparse row, warm-up + one timed run."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from dbscan_tpu.ops.sparse import sparse_cosine_dbscan

    x, _blob_of, _k = make_sparse_anchor(cpu_n)
    maxpp = int(os.environ.get("BENCH_SPARSE_MAXPP", "4096"))
    kw = dict(eps=0.05, min_points=5, max_points_per_partition=maxpp)
    sparse_cosine_dbscan(x, **kw)
    t0 = time.perf_counter()
    sparse_cosine_dbscan(x, **kw)
    np.savez(out_path, seconds=time.perf_counter() - t0, n=cpu_n)


def child_m100(ckpt_dir: str, out_path: str) -> None:
    """One leg of the 100M exact-recovery campaign: generate the
    deterministic euclid anchor, run train(checkpoint_dir=...) so every
    pulled compact chunk persists as a restart point, score exact
    recovery, and write the result npz. A TPU-worker death kills this
    process; the parent (m100_row) counts banked chunks and relaunches.
    Reference analog: the partition-bounded scaling contract,
    DBSCAN.scala:53-56, where Spark lineage replays lost partitions."""
    n = int(os.environ.get("BENCH_100M_N", "100000000"))
    maxpp = int(os.environ.get("BENCH_100M_MAXPP", "262144"))
    pts, blob_of, n_blob, k, eps = make_anchor_cached(n, "euclidean")
    from dbscan_tpu import Engine, train
    from dbscan_tpu.utils.ari import adjusted_rand_index

    t0 = time.perf_counter()
    model = train(
        pts,
        eps=eps,
        min_points=MIN_POINTS,
        max_points_per_partition=maxpp,
        engine=Engine.ARCHERY,
        checkpoint_dir=ckpt_dir,
    )
    dt = time.perf_counter() - t0
    ari = adjusted_rand_index(model.clusters[:n_blob], blob_of)
    tmp = out_path + ".tmp.npz"
    with open(tmp, "wb") as f:
        np.savez(
            f,
            seconds=dt,
            clusters=model.n_clusters,
            expect=k,
            ari=float(ari),
            dup=float(model.stats.get("duplication_factor", 0.0)),
            n_partitions=int(model.stats.get("n_partitions", 0)),
            resumed=bool(model.stats.get("resumed_from_checkpoint", False)),
        )
    os.replace(tmp, out_path)


def m100_row(prefix: str = "m100") -> dict:
    """The 100M campaign as a HARNESS row (VERDICT r4 item 1), riding
    the elastic campaign driver (dbscan_tpu/campaign.py::run_frontier):
    a bounded lease loop around child_m100 subprocess legs — one fresh
    process per leg so a dead TPU backend can never wedge the capture —
    banking phase-1 chunk checkpoints across legs and reporting partial
    progress (chunks_done/chunks_total from the driver's plan-derived
    progress.json) even when every leg dies at the tunneled worker's
    ~4-25-min endurance limit. The campaign driver supplies the
    measured-honesty rules this row always had (stall breakout — now on
    the sidecar's monotone chunk-write counter with mtime as fallback —
    budget-capped leg timeouts, campaign-key invalidation hoisted into
    campaign.ensure_campaign_key) plus the priced replay budget:
    ``{prefix}_campaign_replay_frac`` (= replayed wall / total work
    wall, pro-rata over unbanked chunks) is stamped on the row,
    promoted by obs/bench_history, and gated regress-up by obs/regress.
    Runs LAST so a worker death cannot take the other rows with it.
    Knobs: BENCH_100M_{N,MAXPP,CKPT,LEGS,BUDGET_S,LEG_TIMEOUT_S,
    REST_S}."""
    from dbscan_tpu import campaign as campaign_mod
    from dbscan_tpu.parallel import checkpoint as ckpt_mod

    ckpt_dir = os.environ.get("BENCH_100M_CKPT", "/tmp/ckpt100m")
    max_legs = int(os.environ.get("BENCH_100M_LEGS", "3"))
    budget = float(os.environ.get("BENCH_100M_BUDGET_S", "1500"))
    leg_timeout = float(os.environ.get("BENCH_100M_LEG_TIMEOUT_S", "3600"))
    rest = float(os.environ.get("BENCH_100M_REST_S", "45"))
    os.makedirs(ckpt_dir, exist_ok=True)
    out_path = os.path.join(ckpt_dir, "leg_result.npz")
    try:  # a stale result from an older campaign must not count
        os.unlink(out_path)
    except OSError:
        pass
    env = dict(os.environ)
    # resume compatibility is keyed on these (chunk files are budget-
    # stamped; group_slots is in the run fingerprint) — default to the
    # campaign's proven fine-grained restart config, but an operator
    # override wins. 4194304 (not 8388608): the r5 campaign measured
    # time-to-first-banked-chunk on a resumed leg at ~4 min with this
    # grain vs ~5.5 min at 8388608 — inside the tunneled worker's BAD
    # endurance windows (~6 min), so even flaky legs bank progress; the
    # completing campaign ran at exactly this config.
    env.setdefault("DBSCAN_EAGER_PULL", "1")
    env.setdefault("DBSCAN_COMPACT_CHUNK_SLOTS", "4194304")
    env.setdefault("DBSCAN_GROUP_SLOTS", "4194304")
    campaign_mod.ensure_campaign_key(
        ckpt_dir,
        {
            "n": int(os.environ.get("BENCH_100M_N", "100000000")),
            "maxpp": int(os.environ.get("BENCH_100M_MAXPP", "262144")),
            "chunk_slots": env["DBSCAN_COMPACT_CHUNK_SLOTS"],
            "group_slots": env["DBSCAN_GROUP_SLOTS"],
        },
    )
    # chunks already banked by PRIOR campaigns: when > 0, this
    # campaign's wall covers only the tail of the work, so no
    # throughput figure can honestly be derived from it
    prior_chunks = ckpt_mod.count_p1_chunks(ckpt_dir)
    fr = campaign_mod.run_frontier(
        ckpt_dir,
        [
            sys.executable,
            os.path.abspath(__file__),
            "--m100-child",
            ckpt_dir,
            out_path,
        ],
        env=env,
        max_leases=max_legs,
        budget_s=budget,
        leg_timeout_s=leg_timeout,
        rest_s=rest,
        success_path=out_path,
    )
    result = None
    if fr.complete and os.path.exists(out_path):
        with np.load(out_path) as z:
            result = {k: z[k].item() for k in z.files}
    out = {
        f"{prefix}_n": int(os.environ.get("BENCH_100M_N", "100000000")),
        f"{prefix}_legs": fr.legs,
        f"{prefix}_chunks_done": fr.chunks_done,
        f"{prefix}_chunks_total": fr.chunks_total,
        f"{prefix}_wall_s": round(fr.wall_s, 1),
        f"{prefix}_complete": bool(result),
        # priced restart overhead: the share of the campaign's work
        # wall that bought chunks a later leg had to recompute (gated
        # regress-up against bench/history.jsonl)
        f"{prefix}_campaign_replay_frac": fr.replay_frac,
    }
    last_err = fr.last_error
    if result:
        out.update(
            {
                # completing LEG's wall only (a resumed leg may have
                # done nothing but load checkpoints and merge)
                f"{prefix}_leg_seconds": round(result["seconds"], 1),
                f"{prefix}_clusters": int(result["clusters"]),
                f"{prefix}_expect": int(result["expect"]),
                f"{prefix}_ari": round(result["ari"], 6),
                f"{prefix}_dup": round(result["dup"], 3),
                f"{prefix}_resumed": bool(result["resumed"]),
                f"{prefix}_prior_chunks": prior_chunks,
            }
        )
        if prior_chunks == 0:
            # the campaign did ALL the work: its wall (datagen + every
            # leg + rests) is an honest end-to-end elapsed time. A
            # campaign that finished atop prior campaigns' chunks gets
            # NO mpts — its wall covers only the tail.
            out[f"{prefix}_mpts"] = round(
                out[f"{prefix}_n"] / out[f"{prefix}_wall_s"] / 1e6, 4
            )
    elif last_err:
        out[f"{prefix}_last_error"] = last_err[:200]
    return out


def _rep_obs_fields(delta: dict, dt: float) -> dict:
    """Per-rep observability fields from an obs counter delta: the
    upload/compute wall split and the resident-cache hot/cold tag that
    turn the cosine capture swing (5-60 s same-day, VERDICT r5) into
    two labeled distributions. ``upload_s`` is the host wall spent in
    the resident-payload upload (0.0 on a cache-hit rep — and for
    metrics with no resident payload); ``compute_s`` is the rest of the
    rep's wall. ``resident_hot`` appears only when the rep touched the
    resident cache at all (cosine resident mode)."""
    upload_s = float(delta.get("transfer.payload_upload_s", 0.0))
    out = {
        "upload_s": round(upload_s, 3),
        "compute_s": round(max(0.0, dt - upload_s), 3),
        "upload_bytes": int(delta.get("transfer.payload_upload_bytes", 0)),
    }
    hits = int(delta.get("resident_cache.hits", 0))
    misses = int(delta.get("resident_cache.misses", 0))
    if hits or misses:
        out["resident_hot"] = hits > 0 and misses == 0
    # device-busy share of the rep wall, from the devtime ready-sync
    # brackets (obs/devtime.py): the MEASURED device-time figure the
    # host-inferred ratios get checked against. Absent when the rep ran
    # no bracketed dispatch (devtime off / no tracked dispatch).
    if delta.get("devtime.samples"):
        dev_s = float(delta.get("devtime.device_s", 0.0))
        out["device_busy_frac"] = round(min(1.0, dev_s / dt), 4)
    return out


def run_train(pts, maxpp, use_pallas=False, reps=1, **extra):
    from dbscan_tpu import Engine, obs, train
    from dbscan_tpu.lint import shapecheck
    from dbscan_tpu.obs import devtime as devtime_mod

    kw = dict(
        eps=EPS,
        min_points=MIN_POINTS,
        max_points_per_partition=maxpp,
        engine=Engine.ARCHERY,
        use_pallas=use_pallas,
    )
    kw.update(extra)
    # graftshape cross-check rides every bench run: a pure-Python
    # unification per dispatch (microseconds against the walls timed
    # here) buys the hbm_pred_ratio gate — observed HBM peak vs the
    # static model's predicted envelope — on backends with allocator
    # stats. Enabled/disabled exception-safely (cli.py's obs discipline,
    # PR 3): a raising warm-up or rep must not leave the checker on for
    # callers that had it off.
    sc_was_on = shapecheck.enabled()
    shapecheck.enable()
    # devtime ready-sync brackets ride the bench run the same way: the
    # per-dispatch block_until_ready serializes the dispatch tail (the
    # DBSCAN_TIME_DEVICE trade, made per-family), buying the MEASURED
    # device_busy_frac figure on every headline/anchor row — the
    # device-side ground truth the host-inferred ratios (pull_overlap,
    # compute_s) get gated against. BENCH_DEVTIME=0 opts a capture out
    # when the sync bias must be zero (e.g. record-attempt TPU walls).
    dev_was_on = devtime_mod.enabled()
    if os.environ.get("BENCH_DEVTIME", "1") == "1":
        devtime_mod.enable()
    try:
        # compile warm-up on identical shapes, then best-of-reps timed
        # runs: the TPU is reached over a shared tunnel whose transfer
        # latency fluctuates by >3x between runs, so a single timing is
        # a lottery — the minimum is the reproducible peak-throughput
        # figure
        train(pts, **kw)
        # in-memory obs registry (no trace file unless DBSCAN_TRACE is
        # set): per-rep counter deltas label each timed rep
        # resident-hot/cold and split its upload wall from compute —
        # the disabled-path hooks the pipeline already carries become
        # live for pennies (a few hundred counter bumps per run, vs
        # seconds-scale walls)
        st = obs.enable()
        # suspend the trace file during the timed loop: train() flushes
        # the CUMULATIVE trace at every return, and serializing the
        # warm-up + all prior reps' spans inside a timed rep would bias
        # the very walls (and compute_s) this instrumentation exists to
        # clean up
        trace_path, st.trace_path = st.trace_path, None
        dt = float("inf")
        model = None
        rep_obs: dict = {}
        try:
            for _ in range(max(1, reps)):
                snap = obs.counters()
                t0 = time.perf_counter()
                m = train(pts, **kw)
                dt_rep = time.perf_counter() - t0
                if dt_rep < dt:  # keep the BEST rep's model: its phase
                    model, dt = m, dt_rep  # split describes the wall
                    rep_obs = _rep_obs_fields(
                        obs.counters_delta(snap), dt_rep
                    )
                    # pull-pipeline overlap share, straight from the
                    # rep's stats (pipeline.delta_totals is the ONE
                    # place the ratio is computed); absent on serial
                    # (DBSCAN_PULL_PIPELINE=0) reps, which therefore
                    # never gate against pipelined history
                    pull = m.stats.get("pull")
                    if pull and pull.get("busy_s", 0) > 0:
                        rep_obs["pull_overlap_ratio"] = (
                            pull["overlap_ratio"]
                        )
        finally:
            st.trace_path = trace_path
            obs.flush()  # one untimed write covering all reps
        # observed HBM peak vs the static model's predicted envelope:
        # the graftshape containment figure (obs/regress.py hard-gates
        # it at <= 1.0 — an observed peak above the prediction means
        # the static model stopped being an upper bound). Both sides
        # come from THIS run's shapecheck runtime: the allocator's own
        # peak_bytes_in_use is process-monotone, so a second run_train
        # in the same process would inherit the first run's peak and
        # spuriously break the cap. Absent on stat-less backends (CPU)
        # and when no tracked dispatch ran.
        predicted = shapecheck.predicted_peak()
        observed = shapecheck.observed_peak()
        if predicted and observed:
            rep_obs["hbm_pred_ratio"] = round(observed / predicted, 4)
        return model, dt, rep_obs
    finally:
        if not sc_was_on:
            shapecheck.disable()
        if not dev_was_on:
            devtime_mod.disable()


def child_cpu(data_path: str, out_path: str, maxpp: int) -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    pts = np.load(data_path)["pts"]
    model, dt, _rep_obs = run_train(pts, maxpp)
    np.savez(out_path, clusters=model.clusters, seconds=dt, n=len(pts))


# --- multichip capture (ROADMAP item 1) --------------------------------
#
# The MULTICHIP_* harness used to be an 8-virtual-device correctness
# dryrun (__graft_entry__.dryrun_multichip) — no throughput, no shard
# accounting. This is the real capture: N actual jax.distributed
# processes (gloo CPU collectives here, DCN on a pod), each owning
# dev-per-proc devices of ONE global mesh, running the banded campaign
# with the collective halo-merge and collective-aware pulls. The parent
# computes Mpts/s, merges the per-shard trace files
# (obs/analyze.merge_shards — the flightrec --merge machinery) into the
# all-shard busy share, and pins per-shard dispatch counts plus the
# zero-recompile second run. Keys ride the existing suffix promotions
# (_mpts / _seconds / _busy_frac / _overlap_ratio), so the capture
# trends and gates in bench/history.jsonl like every other row.


def child_multichip(pid: int, n_procs: int, port: int, data_path: str,
                    out_path: str) -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    from dbscan_tpu.parallel.mesh import initialize_multihost

    mesh = initialize_multihost(f"localhost:{port}", n_procs, pid)
    from dbscan_tpu import Engine, obs, train

    pts = np.load(data_path)["pts"]
    kw = dict(
        eps=EPS,
        min_points=MIN_POINTS,
        max_points_per_partition=int(os.environ.get("BENCH_MC_MAXPP", "8192")),
        engine=Engine.ARCHERY,
        neighbor_backend="banded",
        mesh=mesh,
    )
    train(pts, **kw)  # compile warm-up on identical shapes
    snap = obs.counters()
    t0 = time.perf_counter()
    m = train(pts, **kw)
    dt = time.perf_counter() - t0
    delta = obs.counters_delta(snap)
    # zero-recompile pin: a second same-shaped sharded run must hit the
    # jit cache for every family (the ladder discipline extended to the
    # halo-merge widths)
    snap2 = obs.counters()
    train(pts, **kw)
    recompiles = obs.counters_delta(snap2).get("compiles.total", 0)
    pull = m.stats.get("pull") or {}
    row = {
        "pid": pid,
        "seconds": round(dt, 6),
        "n": int(len(pts)),
        "n_clusters": int(m.n_clusters),
        "clusters_sum": int(m.clusters.astype(np.int64).sum()),
        "dispatches": int(delta.get("devtime.samples", 0)),
        "device_s": round(float(delta.get("devtime.device_s", 0.0)), 6),
        "halo_rounds": int(delta.get("halo.rounds", 0)),
        "halo_edges": int(delta.get("halo.edges", 0)),
        "pull_jobs": int(pull.get("jobs", 0)),
        "pull_overlap_ratio": float(pull.get("overlap_ratio", 0.0)),
        "recompiles_second_run": int(recompiles),
    }
    obs.flush()  # write this shard's trace file before reporting
    with open(out_path, "w") as f:
        json.dump(row, f)


def multichip_row(n_procs: int = 2, dev_per_proc: int = 4) -> dict:
    """Spawn the real multi-process capture and assemble the
    MULTICHIP row; returns a ``skipped`` row (never raises) when the
    platform cannot host the process fleet."""
    tmp = tempfile.mkdtemp(prefix="bench_mc_")
    try:
        return _multichip_row_inner(n_procs, dev_per_proc, tmp)
    except Exception as e:  # noqa: BLE001 — the contract is one JSON row
        return {
            "multichip_skipped": "error",
            "multichip_error": f"{type(e).__name__}: {e}"[:2000],
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _multichip_row_inner(n_procs: int, dev_per_proc: int, tmp: str) -> dict:
    import socket

    from dbscan_tpu.obs import analyze as obs_analyze

    mc_n = int(os.environ.get("BENCH_MC_N", "200000"))
    pts = make_data(mc_n)
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    data_path = os.path.join(tmp, "pts.npz")
    np.savez(data_path, pts=pts)
    trace_path = os.path.join(tmp, "mc_trace.jsonl")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={dev_per_proc}"
    )
    env["DBSCAN_TRACE"] = trace_path  # per-process shards <path>.<i>
    env["DBSCAN_DEVTIME"] = "1"  # per-shard dispatch counts + device_s
    # strip sitecustomize-bearing plugin paths (the tunneled-TPU plugin
    # would pre-empt jax.distributed.initialize in the children) — the
    # same filter the CPU re-exec applies
    keep = [
        p
        for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p
        and p != REPO
        and not os.path.exists(os.path.join(p, "sitecustomize.py"))
    ]
    env["PYTHONPATH"] = os.pathsep.join([REPO] + keep)
    # children log to per-process FILES, never PIPEs: the fleet shares
    # one global mesh, so a child blocked on a full stdout pipe inside a
    # collective would wedge every other child — and the parent's
    # sequential communicate() would sit on the wrong process while it
    # happened. Files also survive a kill for the diagnostic tail.
    procs = []
    logs = [os.path.join(tmp, f"log{pid}.txt") for pid in range(n_procs)]
    for pid in range(n_procs):
        with open(logs[pid], "wb") as logf:
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable, os.path.abspath(__file__),
                        "--multichip-child", str(pid), str(n_procs),
                        str(port), data_path,
                        os.path.join(tmp, f"row{pid}.json"),
                    ],
                    env=env,
                    stdout=logf,
                    stderr=subprocess.STDOUT,
                )
            )

    def _tails():
        out = []
        for lg in logs:
            try:
                with open(lg, errors="replace") as f:
                    out.append(f.read()[-2000:])
            except OSError:
                out.append("")
        return "\n---\n".join(out)

    # ONE deadline for the whole fleet (the children run in lockstep on
    # the shared mesh, so per-process sequential timeouts would stack)
    deadline = time.monotonic() + 1800
    try:
        for p in procs:
            p.wait(timeout=max(1.0, deadline - time.monotonic()))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        return {
            "multichip_skipped": "timeout",
            "multichip_child_tail": _tails(),
        }
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any(p.returncode != 0 for p in procs):
        return {
            "multichip_skipped": "child_failed",
            "multichip_child_tail": _tails(),
        }
    rows = []
    for pid in range(n_procs):
        with open(os.path.join(tmp, f"row{pid}.json")) as f:
            rows.append(json.load(f))
    # every shard must agree on the labels it computed (replicated host
    # phases): the cross-process correctness half of the capture
    assert len({r["clusters_sum"] for r in rows}) == 1, rows
    assert len({r["n_clusters"] for r in rows}) == 1, rows
    dt = max(r["seconds"] for r in rows)  # the job is as slow as its
    n_dev = n_procs * dev_per_proc  # slowest shard
    out = {
        "multichip_n": mc_n,
        "multichip_processes": n_procs,
        "multichip_devices": n_dev,
        "multichip_seconds": round(dt, 6),
        "multichip_mpts": round(mc_n / dt / 1e6, 5),
        "multichip_n_clusters": rows[0]["n_clusters"],
        # pinned per-shard dispatch counts: the scaling-shape evidence
        # (each shard issues the same dispatch sequence)
        "multichip_shard_dispatches": [r["dispatches"] for r in rows],
        "multichip_shard_pull_jobs": [r["pull_jobs"] for r in rows],
        "multichip_halo_rounds": rows[0]["halo_rounds"],
        "multichip_halo_edges": rows[0]["halo_edges"],
        # collective-aware pulls: active on every shard, ratio stamped
        # per shard; the promoted scalar is the weakest shard's
        "multichip_pull_overlap_ratio": min(
            r["pull_overlap_ratio"] for r in rows
        ),
        "multichip_recompiles": max(
            r["recompiles_second_run"] for r in rows
        ),
    }
    # all-shard busy share from the merged per-shard traces (the
    # obs.analyze --merge machinery): busy wall where EVERY shard is
    # busy / merged wall — the figure ROADMAP item 1 gates at > 0.8
    shard_files = sorted(
        p for p in (f"{trace_path}.{i}" for i in range(n_procs))
        if os.path.exists(p)
    )
    if len(shard_files) == n_procs:
        merged = obs_analyze.merge_shards(shard_files)
        mg = merged.get("merge") or {}
        if mg.get("wall_s"):
            out["multichip_all_busy_frac"] = round(
                mg["all_busy_s"] / mg["wall_s"], 4
            )
            out["multichip_shard_busy_frac"] = round(
                min(s["busy_s"] for s in mg["shards"]) / mg["wall_s"], 4
            )
    return out


def serve_row(prefix: str = "serve") -> dict:
    """The serving capture (dbscan_tpu/serve): sustained query QPS and
    latency percentiles UNDER SIMULTANEOUS INGEST (the acceptance
    figure: query p50 well under the streaming batch period), plus the
    multi-tenant JobBatcher throughput. Honesty rules: one un-timed
    warm update + warm query + warm tenancy flush first, so the timed
    window measures the resident steady state (the jit cache is the
    whole point of the serving layer), and latencies are only recorded
    while the ingest thread has batches in flight."""
    import threading

    from dbscan_tpu.serve import ClusterService, JobBatcher, synthetic

    n_updates = int(os.environ.get("BENCH_SERVE_UPDATES", "5"))
    batch_n = int(os.environ.get("BENCH_SERVE_BATCH", "20000"))
    qbatch = int(os.environ.get("BENCH_SERVE_QBATCH", "256"))
    readers = int(os.environ.get("BENCH_SERVE_READERS", "2"))
    n_jobs = int(os.environ.get("BENCH_SERVE_JOBS", "200"))
    rng = np.random.default_rng(7)

    side = 6
    centers = synthetic.blob_centers(side=side)

    def mk_batch(u: int) -> np.ndarray:
        return synthetic.drifting_batch(
            rng, u, batch_n, centers, drift=0.1
        )

    qpts = rng.uniform(0, side * 8.0, (qbatch, 2))
    lat_ms: list = []
    lat_lock = threading.Lock()
    stop = threading.Event()
    record = threading.Event()

    svc = ClusterService(
        0.6, 5, max_points_per_partition=8192, window=3
    )

    def reader():
        while not stop.is_set():
            t0 = time.perf_counter()
            svc.query(qpts)
            dt = (time.perf_counter() - t0) * 1e3
            if record.is_set():
                with lat_lock:
                    lat_ms.append(dt)

    with svc:
        # warm through a FULL window of updates: the skeleton size
        # plateaus once expiry balances additions, so the timed window
        # measures the steady state instead of paying a fresh query-
        # kernel signature every time the growing skeleton crosses a
        # ladder rung
        warm = 3
        for u in range(warm):
            svc.submit(mk_batch(u))
        svc.drain()
        svc.query(qpts)  # warm query signature at the plateau rung
        threads = [
            threading.Thread(target=reader, daemon=True)
            for _ in range(max(1, readers))
        ]
        for t in threads:
            t.start()
        # fresh live windows sized to cover the whole timed leg: the
        # stamped serve_windowed_* figures then describe EXACTLY the
        # timed population (warm-pass compile walls excluded), so they
        # are comparable with the lats-derived percentiles committed
        # beside them (the live-vs-offline agreement pin)
        prev_win = os.environ.get("DBSCAN_OBS_WINDOW_S")
        os.environ["DBSCAN_OBS_WINDOW_S"] = "600"
        from dbscan_tpu.obs import live as obs_live

        obs_live.reset()
        obs_live.ensure_env()
        record.set()
        t0 = time.perf_counter()
        for u in range(warm, warm + n_updates):
            svc.submit(mk_batch(u))
        svc.drain()
        wall = time.perf_counter() - t0
        record.clear()
        stop.set()
        for t in threads:
            t.join(timeout=30)
        health = svc.health()
        windowed_p99 = obs_live.quantile("serve.query_ms", 0.99)
        windowed_qps = obs_live.rate("serve.queries")
        if prev_win is None:
            os.environ.pop("DBSCAN_OBS_WINDOW_S", None)
        else:
            os.environ["DBSCAN_OBS_WINDOW_S"] = prev_win

    with lat_lock:
        lats = np.asarray(lat_ms, np.float64)

    # tenancy leg: warm one small flush, then the timed mixed stream
    batcher = JobBatcher()

    def mk_job():
        return synthetic.tenant_job(rng)

    for _ in range(3):
        batcher.submit(mk_job(), eps=0.5, min_points=4)
    batcher.flush()  # warm the serve.jobs signature
    for _ in range(n_jobs):
        batcher.submit(mk_job(), eps=0.5, min_points=4)
    t0 = time.perf_counter()
    done = batcher.flush()
    tenancy_wall = time.perf_counter() - t0

    row = {
        f"{prefix}_updates": n_updates,
        f"{prefix}_batch_points": batch_n,
        f"{prefix}_batch_period_s": round(wall / max(1, n_updates), 4),
        f"{prefix}_resident_points": int(health["resident_points"]),
        f"{prefix}_queries": int(len(lats)),
        f"{prefix}_qps": round(float(len(lats) / wall), 3) if wall > 0 else 0.0,
        "tenancy_jobs": len(done),
        "tenancy_jobs_s": round(float(len(done) / tenancy_wall), 3)
        if tenancy_wall > 0
        else 0.0,
    }
    if len(lats):
        row[f"{prefix}_p50_ms"] = round(float(np.percentile(lats, 50)), 3)
        row[f"{prefix}_p99_ms"] = round(float(np.percentile(lats, 99)), 3)
    if windowed_p99 is not None:
        row[f"{prefix}_windowed_p99_ms"] = round(float(windowed_p99), 3)
        row[f"{prefix}_windowed_qps"] = round(float(windowed_qps), 3)
    return row


def serve_replicated_row(max_replicas: int, prefix: str = "serve") -> dict:
    """The replicated-serving capture (serve/sharded.py + router.py):
    for each replica count on the ladder 1..max_replicas, sustained
    ROUTED query QPS and latency percentiles under simultaneous sharded
    ingest with a FIXED reader pool — the rung axis isolates read-side
    scaling (more replicas absorbing the same offered load), which is
    the acceptance figure: QPS grows with the ladder while p99 stays
    well under the ingest batch period. Honesty rules match serve_row:
    every rung re-ingests the SAME deterministic schedule into a fresh
    service (re-seeded rng per rung), warms a full window plus the
    routed query signatures before timing, and records latencies only
    while ingest is in flight. The shed governor is ARMED during each
    timed window at a generous bound (BENCH_SERVE_SHED_BOUND_MS,
    default 5000): a healthy run sheds nothing, so the committed
    ``serve_shed_frac`` of 0.0 regressing UP means p99 actually drifted
    past the declared bound — the gate catches capacity loss, not a
    tuning choice."""
    import threading

    from dbscan_tpu.serve import (
        QueryRouter,
        QueryShed,
        ShardedClusterService,
        synthetic,
    )

    n_updates = int(os.environ.get("BENCH_SERVE_UPDATES", "5"))
    batch_n = int(os.environ.get("BENCH_SERVE_BATCH", "20000"))
    qbatch = int(os.environ.get("BENCH_SERVE_QBATCH", "256"))
    readers = max(1, int(os.environ.get("BENCH_SERVE_READERS", "4")))
    n_shards = int(os.environ.get("BENCH_SERVE_SHARDS", "2"))
    shed_bound = os.environ.get("BENCH_SERVE_SHED_BOUND_MS", "5000")

    side = 6
    row: dict = {
        f"{prefix}_replicas": int(max_replicas),
        f"{prefix}_shards": n_shards,
        f"{prefix}_updates": n_updates,
        f"{prefix}_batch_points": batch_n,
        f"{prefix}_readers": readers,
    }
    shed_total = routed_total = 0
    prev_bound = os.environ.get("DBSCAN_SERVE_SHED_P99_MS")
    for n_rep in range(1, int(max_replicas) + 1):
        # identical deterministic schedule per rung: the rng is
        # re-seeded so every rung ingests the same batches and offers
        # the same query mix — the rung axis varies ONLY the replica
        # count
        rng = np.random.default_rng(11)
        centers = synthetic.blob_centers(side=side)

        def mk_batch(u: int) -> np.ndarray:
            return synthetic.drifting_batch(
                rng, u, batch_n, centers, drift=0.1
            )

        # several distinct query payloads per reader slot: content
        # routing hashes each payload to a replica, so a rotating mix
        # spreads the offered load without scripting the router
        q_list = [
            rng.uniform(0.0, side * 8.0, (qbatch, 2))
            for _ in range(4 * readers)
        ]
        lat_ms: list = []
        lat_lock = threading.Lock()
        stop = threading.Event()
        record = threading.Event()

        svc = ShardedClusterService(
            0.6, 5, n_shards=n_shards,
            max_points_per_partition=8192, window=3,
        )

        with svc:
            warm = 3
            for u in range(warm):
                svc.submit(mk_batch(u))
            svc.drain()
            router = QueryRouter(svc, replicas=n_rep)

            def reader(slot: int, router=router, q_list=q_list) -> None:
                i = slot
                while not stop.is_set():
                    q = q_list[i % len(q_list)]
                    i += readers
                    t0 = time.perf_counter()
                    try:
                        router.query(q)
                    except QueryShed:
                        continue  # counted by the router; not a wall
                    dt = (time.perf_counter() - t0) * 1e3
                    if record.is_set():
                        with lat_lock:
                            lat_ms.append(dt)

            try:
                for q in q_list:
                    router.query(q)  # warm every payload's route
                threads = [
                    threading.Thread(target=reader, args=(s,), daemon=True)
                    for s in range(readers)
                ]
                for t in threads:
                    t.start()
                # arm the shed governor for the timed window only: the
                # warm pass above may carry one-time compile walls that
                # would otherwise poison the rolling p99. The live
                # windows reset with it — sized to cover the whole
                # timed leg, so the stamped serve_windowed_* figures
                # describe exactly the timed population (the
                # live-vs-offline agreement pin)
                prev_win = os.environ.get("DBSCAN_OBS_WINDOW_S")
                os.environ["DBSCAN_OBS_WINDOW_S"] = "600"
                from dbscan_tpu.obs import live as obs_live

                obs_live.reset()
                obs_live.ensure_env()
                os.environ["DBSCAN_SERVE_SHED_P99_MS"] = shed_bound
                record.set()
                t0 = time.perf_counter()
                for u in range(warm, warm + n_updates):
                    svc.submit(mk_batch(u))
                svc.drain()
                wall = time.perf_counter() - t0
                record.clear()
                stop.set()
                for t in threads:
                    t.join(timeout=30)
                h = router.health()
                shed_total += h["shed"]
                routed_total += h["routed"]
                windowed_p99 = obs_live.quantile("serve.query_ms", 0.99)
                windowed_qps = obs_live.rate(
                    "serve.router.routed"
                ) + obs_live.rate("serve.queries")
                if prev_win is None:
                    os.environ.pop("DBSCAN_OBS_WINDOW_S", None)
                else:
                    os.environ["DBSCAN_OBS_WINDOW_S"] = prev_win
            finally:
                if prev_bound is None:
                    os.environ.pop("DBSCAN_SERVE_SHED_P99_MS", None)
                else:
                    os.environ["DBSCAN_SERVE_SHED_P99_MS"] = prev_bound
                router.close()

        with lat_lock:
            lats = np.asarray(lat_ms, np.float64)
        row[f"{prefix}_r{n_rep}_queries"] = int(len(lats))
        row[f"{prefix}_r{n_rep}_qps"] = (
            round(float(len(lats) / wall), 3) if wall > 0 else 0.0
        )
        if len(lats):
            row[f"{prefix}_r{n_rep}_p50_ms"] = round(
                float(np.percentile(lats, 50)), 3
            )
            row[f"{prefix}_r{n_rep}_p99_ms"] = round(
                float(np.percentile(lats, 99)), 3
            )
        if windowed_p99 is not None:
            # top rung's figure survives, like rep_batch_period_s
            row[f"{prefix}_windowed_p99_ms"] = round(
                float(windowed_p99), 3
            )
            row[f"{prefix}_windowed_qps"] = round(float(windowed_qps), 3)
        # the top rung's figure survives: the acceptance inequality
        # (p99 well under the batch period) is read at the top rung.
        # Distinct key from serve_row's serve_batch_period_s — the
        # replicated row's ingest period (sharded service + router
        # reader pool) is a DIFFERENT population, and the gate must
        # not mix populations under one metric
        row[f"{prefix}_rep_batch_period_s"] = round(
            wall / max(1, n_updates), 4
        )
    total = shed_total + routed_total
    row[f"{prefix}_shed_frac"] = (
        round(shed_total / total, 6) if total else 0.0
    )
    return row


def make_embed_anchor(n: int, d: int):
    """Engineered embed workload in the regime the LSH front-end is
    built for (tight-threshold near-duplicate clustering): K unit-
    sphere hotspots with sub-eps noise plus random-direction outliers.
    Returns (points f32, blob_of [n_blob], n_blob, K, eps)."""
    rng = np.random.default_rng(42)
    k = max(16, n // 400)
    n_noise = n // 50
    n_blob = n - n_noise
    blob_of = rng.integers(0, k, n_blob)
    centers = rng.standard_normal((k, d)).astype(np.float32)
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    pts = rng.standard_normal((n, d), dtype=np.float32)
    pts[:n_blob] *= np.float32(0.0002)
    pts[:n_blob] += centers[blob_of]
    # eps 0.001: duplication band sqrt(2*eps) ~ 0.045 sits under the
    # ~1/sqrt(D) projected spread at the default D, so the LSH binning
    # front-end engages (the regime the engine is built for) instead
    # of degrading everything to the spill fallback
    return pts, blob_of, n_blob, k, 0.001


def embed_row(prefix: str = "embed") -> dict:
    """The embed-engine capture (`bench.py --embed`): exact-path
    throughput + construction accuracy, then the subsampled-edge run
    whose ARI vs the exact path is THE gated accuracy figure
    (`embed_ari`, regress-down; declared floor in PARITY.md "Embed
    accuracy contract"). Same discipline as the other rows: full warm
    run first (bucket shapes are ladder rungs of the same workload, so
    the warm run settles every W rung and jit signature), best-of-reps
    timed exact runs, one timed sampled run."""
    import jax

    from dbscan_tpu import embed_dbscan
    from dbscan_tpu.utils.ari import adjusted_rand_index

    on_cpu = jax.default_backend() == "cpu"
    n = int(os.environ.get("BENCH_EMBED_N", "20000" if on_cpu else "500000"))
    d = int(os.environ.get("BENCH_EMBED_D", "128"))
    maxpp = int(os.environ.get("BENCH_EMBED_MAXPP", "4096"))
    frac = float(os.environ.get("BENCH_EMBED_SAMPLE_FRAC", "0.25"))
    reps = int(os.environ.get("BENCH_EMBED_REPS", "2"))
    pts, blob_of, n_blob, k, eps = make_embed_anchor(n, d)
    min_points = 5
    kw = dict(max_points_per_partition=maxpp)

    embed_dbscan(pts, eps, min_points, **kw)  # warm: settles W rungs
    dt = float("inf")
    stats: dict = {}
    for _ in range(max(1, reps)):
        rep_stats: dict = {}
        t0 = time.perf_counter()
        exact, _flags = embed_dbscan(
            pts, eps, min_points, stats_out=rep_stats, **kw
        )
        dt_rep = time.perf_counter() - t0
        if dt_rep < dt:
            dt, stats = dt_rep, rep_stats
    construction_ari = adjusted_rand_index(exact[:n_blob], blob_of)

    s_stats: dict = {}
    t0 = time.perf_counter()
    sampled, _sf = embed_dbscan(
        pts, eps, min_points, sample_frac=frac, stats_out=s_stats, **kw
    )
    dt_sample = time.perf_counter() - t0
    sample_ari = adjusted_rand_index(sampled, exact)

    return {
        f"{prefix}_n": n,
        f"{prefix}_d": d,
        f"{prefix}_seconds": round(dt, 3),
        f"{prefix}_mpts": round(n / dt / 1e6, 5),
        f"{prefix}_clusters": int(len(np.unique(exact[exact > 0]))),
        f"{prefix}_expect": k,
        f"{prefix}_construction_ari": round(float(construction_ari), 6),
        # THE accuracy-contract figure: sampled labels vs the exact
        # path at the declared fraction (gated regress-down; floor
        # declared in PARITY.md)
        f"{prefix}_ari": round(float(sample_ari), 6),
        f"{prefix}_ari_floor": 0.95,
        f"{prefix}_sample_frac": frac,
        f"{prefix}_sample_seconds": round(dt_sample, 3),
        f"{prefix}_sample_speedup": round(dt / max(dt_sample, 1e-9), 3),
        f"{prefix}_buckets": int(stats.get("embed_buckets", 0)),
        f"{prefix}_spill_fallbacks": int(
            stats.get("embed_spill_fallbacks", 0)
        ),
        f"{prefix}_dup": round(float(stats.get("duplication_factor", 0)), 4),
        f"{prefix}_escalations": int(stats.get("embed_escalations", 0)),
        f"{prefix}_phases": _phases(stats),
    }


def child_embed1b(ckpt_dir: str, out_path: str) -> None:
    """One leg of the billion-point embed campaign: regenerate the
    deterministic embed anchor, run embed_dbscan(checkpoint_dir=...) so
    every bucket band persists as a restart point, and write the result
    npz (labels crc32 included — the parent's byte-identity check). A
    worker death kills this process; the parent (embed1b_row) counts
    banked bands and relaunches."""
    import zlib

    from dbscan_tpu import embed_dbscan
    from dbscan_tpu.utils.ari import adjusted_rand_index

    n = int(os.environ.get("BENCH_EMBED1B_N", "20000"))
    d = int(os.environ.get("BENCH_EMBED1B_D", "128"))
    maxpp = int(os.environ.get("BENCH_EMBED1B_MAXPP", "2048"))
    pts, blob_of, n_blob, k, eps = make_embed_anchor(n, d)
    stats: dict = {}
    t0 = time.perf_counter()
    clusters, _flags = embed_dbscan(
        pts, eps, 5,
        max_points_per_partition=maxpp,
        checkpoint_dir=ckpt_dir,
        stats_out=stats,
    )
    dt = time.perf_counter() - t0
    ari = adjusted_rand_index(clusters[:n_blob], blob_of)
    tmp = out_path + ".tmp.npz"
    with open(tmp, "wb") as f:
        np.savez(
            f,
            seconds=dt,
            clusters=int(len(np.unique(clusters[clusters > 0]))),
            expect=k,
            ari=float(ari),
            dup=float(stats.get("duplication_factor", 0.0)),
            bands=int(stats.get("campaign_chunks_total", 0)),
            bands_loaded=int(stats.get("campaign_bands_loaded", 0)),
            resumed=bool(stats.get("resumed_from_checkpoint", False)),
            labels_crc=np.uint32(
                zlib.crc32(np.ascontiguousarray(clusters).tobytes())
            ),
        )
    os.replace(tmp, out_path)


def embed1b_row(prefix: str = "embed1b") -> dict:
    """The billion-point embed campaign as a harness row (`bench.py
    --embed1b`, ROADMAP item 1): `campaign.run_frontier` subprocess
    legs around child_embed1b, leasing bucket-band chunks
    (`count_done=engine.count_banked_bands` — the embed restart-point
    grain) so a killed leg's banked bands survive and its replay is
    priced. Stamps the two gated figures: ``embed1b_mpts``
    (regress-down; only when the campaign did ALL the work —
    the m100 prior-chunks honesty rule) and ``embed1b_replay_frac``
    (regress-up; restart overhead as a first-class metric), plus the
    byte-identity verdict ``embed1b_labels_match`` — the campaign's
    final labels crc32 vs a clean uncheckpointed in-process run on the
    regenerated anchor. Knobs: BENCH_EMBED1B_{N,D,MAXPP,CKPT,LEGS,
    BUDGET_S,LEG_TIMEOUT_S,REST_S}."""
    import zlib

    import jax

    from dbscan_tpu import campaign as campaign_mod
    from dbscan_tpu import embed_dbscan
    from dbscan_tpu.embed import engine as embed_engine

    on_cpu = jax.default_backend() == "cpu"
    n = int(
        os.environ.get("BENCH_EMBED1B_N", "20000" if on_cpu else "1000000000")
    )
    os.environ["BENCH_EMBED1B_N"] = str(n)  # children must match
    d = int(os.environ.get("BENCH_EMBED1B_D", "128"))
    maxpp = int(os.environ.get("BENCH_EMBED1B_MAXPP", "2048"))
    ckpt_dir = os.environ.get("BENCH_EMBED1B_CKPT", "/tmp/ckptembed1b")
    max_legs = int(os.environ.get("BENCH_EMBED1B_LEGS", "4"))
    budget = float(os.environ.get("BENCH_EMBED1B_BUDGET_S", "1500"))
    leg_timeout = float(
        os.environ.get("BENCH_EMBED1B_LEG_TIMEOUT_S", "3600")
    )
    rest = float(os.environ.get("BENCH_EMBED1B_REST_S", "5"))
    os.makedirs(ckpt_dir, exist_ok=True)
    out_path = os.path.join(ckpt_dir, "leg_result.npz")
    try:  # a stale result from an older campaign must not count
        os.unlink(out_path)
    except OSError:
        pass
    env = dict(os.environ)
    # band fingerprints are knob-keyed (engine._band_fingerprint), so
    # the campaign key carries everything that invalidates banked bands
    campaign_mod.ensure_campaign_key(
        ckpt_dir,
        {
            "n": n,
            "d": d,
            "maxpp": maxpp,
            "quantizer": env.get("DBSCAN_EMBED_QUANTIZER", "srp"),
            "band": env.get("DBSCAN_EMBED_BAND", "0"),
        },
    )
    # bands already banked by PRIOR campaigns: when > 0, this
    # campaign's wall covers only the tail of the work, so no
    # throughput figure can honestly be derived from it
    prior_bands = embed_engine.count_banked_bands(ckpt_dir)
    fr = campaign_mod.run_frontier(
        ckpt_dir,
        [
            sys.executable,
            os.path.abspath(__file__),
            "--embed1b-child",
            ckpt_dir,
            out_path,
        ],
        env=env,
        max_leases=max_legs,
        budget_s=budget,
        leg_timeout_s=leg_timeout,
        rest_s=rest,
        success_path=out_path,
        count_done=embed_engine.count_banked_bands,
    )
    result = None
    if fr.complete and os.path.exists(out_path):
        with np.load(out_path) as z:
            result = {k: z[k].item() for k in z.files}
    out = {
        f"{prefix}_n": n,
        f"{prefix}_d": d,
        f"{prefix}_legs": fr.legs,
        f"{prefix}_kills": fr.kills,
        f"{prefix}_chunks_done": fr.chunks_done,
        f"{prefix}_chunks_total": fr.chunks_total,
        f"{prefix}_wall_s": round(fr.wall_s, 1),
        f"{prefix}_complete": bool(result),
        # priced restart overhead: the share of the campaign's work
        # wall that bought bands a later leg had to recompute (gated
        # regress-up against bench/history.jsonl)
        f"{prefix}_replay_frac": fr.replay_frac,
    }
    if result:
        out.update(
            {
                f"{prefix}_leg_seconds": round(result["seconds"], 3),
                f"{prefix}_clusters": int(result["clusters"]),
                f"{prefix}_expect": int(result["expect"]),
                f"{prefix}_ari": round(result["ari"], 6),
                f"{prefix}_dup": round(result["dup"], 4),
                f"{prefix}_bands": int(result["bands"]),
                f"{prefix}_resumed": bool(result["resumed"]),
                f"{prefix}_prior_bands": prior_bands,
            }
        )
        if prior_bands == 0:
            out[f"{prefix}_mpts"] = round(
                n / out[f"{prefix}_wall_s"] / 1e6, 4
            )
        # byte-identity across the kill schedule: the campaign's final
        # labels vs a clean uncheckpointed run of the same anchor —
        # the "byte-identical finalize" contract, verified on the
        # capture itself rather than asserted
        pts, _blob_of, _n_blob, _k, eps = make_embed_anchor(n, d)
        clean, _cf = embed_dbscan(
            pts, eps, 5, max_points_per_partition=maxpp
        )
        clean_crc = zlib.crc32(np.ascontiguousarray(clean).tobytes())
        out[f"{prefix}_labels_match"] = bool(
            int(result["labels_crc"]) == clean_crc
        )
    elif fr.last_error:
        out[f"{prefix}_last_error"] = fr.last_error[:200]
    return out


def make_hdbscan_anchor(n: int):
    """Engineered variable-density workload: K blobs whose scales span
    a decade (no single eps labels them all — the density engine's
    reason to exist) plus uniform noise. Returns (points f64,
    blob_of [n_blob], K)."""
    rng = np.random.default_rng(42)
    k = max(6, n // 2000)
    n_noise = n // 20
    n_blob = n - n_noise
    blob_of = rng.integers(0, k, n_blob)
    centers = rng.uniform(0.0, 100.0, (k, 2))
    scales = np.geomspace(0.05, 0.5, k)[rng.permutation(k)]
    pts = centers[blob_of] + rng.normal(size=(n_blob, 2)) * (
        scales[blob_of][:, None]
    )
    noise = rng.uniform(-5.0, 105.0, (n_noise, 2))
    return np.concatenate([pts, noise]), blob_of, k


def hdbscan_row(prefix: str = "hdbscan") -> dict:
    """The density-engine capture (`bench.py --hdbscan`): HDBSCAN*
    throughput (`hdbscan_mpts`, gated regress-down) + the Borůvka MST
    round count (`hdbscan_boruvka_rounds`, gated regress-up as a
    dispatch-depth figure, bounded by ceil(log2 n) + 2) over an
    engineered multi-density workload, with construction ARI as the
    correctness anchor. Same discipline as the other rows: full warm
    run first (ladders/kernels settle), best-of-reps timed runs."""
    from dbscan_tpu import hdbscan
    from dbscan_tpu.utils.ari import adjusted_rand_index

    n = int(os.environ.get("BENCH_HDBSCAN_N", "4000"))
    min_pts = int(os.environ.get("BENCH_HDBSCAN_MIN_PTS", "10"))
    reps = int(os.environ.get("BENCH_HDBSCAN_REPS", "2"))
    pts, blob_of, k = make_hdbscan_anchor(n)
    n_blob = len(blob_of)

    hdbscan(pts, min_pts=min_pts)  # warm: settles ladders + kernels
    dt = float("inf")
    stats: dict = {}
    for _ in range(max(1, reps)):
        rep_stats: dict = {}
        t0 = time.perf_counter()
        labels = hdbscan(pts, min_pts=min_pts, stats_out=rep_stats)
        dt_rep = time.perf_counter() - t0
        if dt_rep < dt:
            dt, stats = dt_rep, rep_stats
    construction_ari = adjusted_rand_index(labels[:n_blob], blob_of)

    return {
        f"{prefix}_n": n,
        f"{prefix}_min_pts": min_pts,
        f"{prefix}_seconds": round(dt, 3),
        f"{prefix}_mpts": round(n / dt / 1e6, 5),
        f"{prefix}_clusters": int(len(np.unique(labels[labels > 0]))),
        f"{prefix}_expect": k,
        f"{prefix}_construction_ari": round(float(construction_ari), 6),
        f"{prefix}_boruvka_rounds": int(stats.get("boruvka_rounds", 0)),
        f"{prefix}_core_chunks": int(stats.get("core_chunks", 0)),
        f"{prefix}_phases": _phases(stats),
    }


def anchor_row(prefix: str, n: int, kind: str, maxpp: int) -> dict:
    """One engineered-structure run: exact cluster count + construction
    ARI are the correctness anchor at scale (no oracle fits >=10M). Same
    timing discipline as the headline number (run_train: compile warm-up,
    best-of-reps) so the row is hot and tunnel-jitter-resistant."""
    from dbscan_tpu.utils.ari import adjusted_rand_index

    pts, blob_of, n_blob, k, eps = make_anchor(n, kind)
    extra = {"eps": eps}
    if kind != "euclidean":
        extra["metric"] = kind
    # cosine reps default to 1: a ~230 s-per-rep row (and its group
    # shapes depend on the partition count, so no subset warm-up exists
    # for it — the warm-up must be full-size too)
    reps = int(
        os.environ.get(
            "BENCH_COS_REPS" if kind == "cosine" else "BENCH_ANCHOR_REPS",
            "1" if kind == "cosine" else "2",
        )
    )
    model, dt, rep_obs = run_train(pts, maxpp, reps=reps, **extra)
    ari = adjusted_rand_index(model.clusters[:n_blob], blob_of)
    out = {
        f"{prefix}_n": n,
        f"{prefix}_seconds": round(dt, 2),
        f"{prefix}_clusters": model.n_clusters,
        f"{prefix}_expect": k,
        f"{prefix}_ari": round(float(ari), 6),
        f"{prefix}_phases": _phases(model.stats),
        # hot/cold + upload/compute split of the BEST rep (obs counters):
        # the cosine wall is only comparable across captures once each
        # rep says whether it paid the resident-payload upload
        **{f"{prefix}_{k2}": v for k2, v in rep_obs.items()},
        # spill wall + level-build rounds (cosine rows; empty for the
        # grid metrics, which never spill)
        **_spill_fields(prefix, model.stats),
        # cellcc finalize wall + device CC sweep count (banded rows;
        # empty for paths with no banded finalize)
        **_cellcc_fields(prefix, model.stats),
    }
    if kind == "euclidean" and os.environ.get("BENCH_MFU", "1") == "1":
        import jax

        if jax.default_backend() != "cpu":
            # supplementary instrumented run: a worker death here must
            # not discard the completed primary measurement above
            try:
                out.update(_mfu_fields(prefix, pts, maxpp, **extra))
            except Exception as e:  # noqa: BLE001 — recorded, not fatal
                out[f"{prefix}_mfu_failed"] = f"{type(e).__name__}"[:80]
    if kind == "cosine":
        cpu_n = int(os.environ.get("BENCH_COS_CPU_N", "50000"))
        out.update(_row_cpu_baseline(prefix, kind, cpu_n, n / dt))
    return out


def _reexec_cpu(why: str, cleanup_dir: str = None) -> None:
    """Replace this process with a CPU-backend re-run of the same argv.
    Shared by the init-probe fallback and the mid-run death fallback —
    the PYTHONPATH filter (drop only sitecustomize-bearing plugin paths,
    keep user entries) must stay identical in both."""
    sys.stderr.write(f"bench: {why}; re-running on the CPU backend\n")
    if cleanup_dir is not None:  # execve skips context-manager exits
        shutil.rmtree(cleanup_dir, ignore_errors=True)
    env = dict(os.environ)
    env["BENCH_NO_TPU_PROBE"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    keep = [
        p
        for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
        if p
        and p != REPO
        and not os.path.exists(os.path.join(p, "sitecustomize.py"))
    ]
    env["PYTHONPATH"] = os.pathsep.join([REPO] + keep)
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


def _ensure_live_backend() -> None:
    """The tunneled TPU plugin hangs JAX backend init (even under
    JAX_PLATFORMS=cpu) whenever the tunnel is down — a bench invocation
    would then block forever instead of producing its JSON line. Probe
    device init in a killable subprocess; on failure re-exec with the
    plugin path stripped so the run degrades to a real CPU measurement
    (backend is reported in the output)."""
    if os.environ.get("BENCH_NO_TPU_PROBE") == "1":
        return
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=180,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        if proc.returncode == 0:
            return
        # fast-crashing plugin init (segfault/fatal raise) must also
        # route to the fallback, not just a hang
        _reexec_cpu(f"accelerator init failed (rc {proc.returncode})")
    except subprocess.TimeoutExpired:
        _reexec_cpu("accelerator init hung (tunnel down?)")


def main() -> None:
    n = int(os.environ.get("BENCH_N", "1000000"))
    maxpp = int(os.environ.get("BENCH_MAXPP", "262144"))
    cpu_maxpp = int(os.environ.get("BENCH_CPU_MAXPP", "2048"))
    cpu_n = int(os.environ.get("BENCH_CPU_N", str(min(n, 100000))))

    if len(sys.argv) >= 4 and sys.argv[1] == "--cpu-child":
        child_cpu(sys.argv[2], sys.argv[3], cpu_maxpp)
        return
    if len(sys.argv) >= 4 and sys.argv[1] == "--cos-child":
        child_cos_cpu(int(sys.argv[2]), sys.argv[3])
        return
    if len(sys.argv) >= 4 and sys.argv[1] == "--sparse-child":
        child_sparse_cpu(int(sys.argv[2]), sys.argv[3])
        return
    if len(sys.argv) >= 4 and sys.argv[1] == "--m100-child":
        child_m100(sys.argv[2], sys.argv[3])
        return
    if len(sys.argv) >= 4 and sys.argv[1] == "--embed1b-child":
        child_embed1b(sys.argv[2], sys.argv[3])
        return
    if len(sys.argv) >= 7 and sys.argv[1] == "--multichip-child":
        child_multichip(
            int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]),
            sys.argv[5], sys.argv[6],
        )
        return
    if len(sys.argv) >= 2 and sys.argv[1] == "--serve":
        # standalone serving capture: the BENCH_SERVE_* shape (QPS +
        # latency-under-ingest + tenancy throughput flat), printed as
        # ONE JSON object and gate-then-appended to BENCH_HISTORY.
        # --replicas N switches to the replicated-serving ladder
        # (sharded service + query router, serve_r{k}_* keys)
        _ensure_live_backend()
        import jax as _jax

        cap = {"metric": "serve", "backend": _jax.default_backend()}
        if "--replicas" in sys.argv:
            n_rep = int(sys.argv[sys.argv.index("--replicas") + 1])
            cap.update(serve_replicated_row(n_rep))
        else:
            cap.update(serve_row())
        print(json.dumps(cap))
        hist_path = os.environ.get("BENCH_HISTORY")
        if hist_path:
            try:
                _history_gate_append(cap, hist_path)
            except Exception as e:  # noqa: BLE001 — never cost the capture
                sys.stderr.write(f"bench: history append failed: {e}\n")
        sys.exit(0)
    if len(sys.argv) >= 2 and sys.argv[1] == "--embed":
        # standalone embed capture: exact throughput + the subsampled
        # accuracy contract (BENCH_EMBED_* knobs), printed as ONE JSON
        # object and gate-then-appended to BENCH_HISTORY — embed_mpts
        # gates regress-down as a throughput, embed_ari regress-down
        # as the declared accuracy floor
        _ensure_live_backend()
        import jax as _jax

        cap = {"metric": "embed", "backend": _jax.default_backend()}
        cap.update(embed_row())
        print(json.dumps(cap))
        hist_path = os.environ.get("BENCH_HISTORY")
        if hist_path:
            try:
                _history_gate_append(cap, hist_path)
            except Exception as e:  # noqa: BLE001 — never cost the capture
                sys.stderr.write(f"bench: history append failed: {e}\n")
        sys.exit(0)
    if len(sys.argv) >= 2 and sys.argv[1] == "--embed1b":
        # billion-point embed frontier campaign (BENCH_EMBED1B_*
        # knobs), printed as ONE JSON object and gate-then-appended to
        # BENCH_HISTORY — embed1b_mpts gates regress-down as a
        # throughput, embed1b_replay_frac regress-up as the priced
        # restart overhead
        _ensure_live_backend()
        import jax as _jax

        cap = {"metric": "embed1b", "backend": _jax.default_backend()}
        cap.update(embed1b_row())
        print(json.dumps(cap))
        hist_path = os.environ.get("BENCH_HISTORY")
        if hist_path:
            try:
                _history_gate_append(cap, hist_path)
            except Exception as e:  # noqa: BLE001 — never cost the capture
                sys.stderr.write(f"bench: history append failed: {e}\n")
        sys.exit(0)
    if len(sys.argv) >= 2 and sys.argv[1] == "--hdbscan":
        # standalone density-engine capture (BENCH_HDBSCAN_* knobs),
        # printed as ONE JSON object and gate-then-appended to
        # BENCH_HISTORY — hdbscan_mpts gates regress-down as a
        # throughput, hdbscan_boruvka_rounds regress-up as a
        # dispatch-depth figure
        _ensure_live_backend()
        import jax as _jax

        cap = {"metric": "hdbscan", "backend": _jax.default_backend()}
        cap.update(hdbscan_row())
        print(json.dumps(cap))
        hist_path = os.environ.get("BENCH_HISTORY")
        if hist_path:
            try:
                _history_gate_append(cap, hist_path)
            except Exception as e:  # noqa: BLE001 — never cost the capture
                sys.stderr.write(f"bench: history append failed: {e}\n")
        sys.exit(0)
    if len(sys.argv) >= 2 and sys.argv[1] == "--multichip":
        # standalone multichip capture: the MULTICHIP_* shape
        # (n_devices/ok/rc + the real row keys flat), printed as ONE
        # JSON object and appended to BENCH_HISTORY when set
        n_procs = int(os.environ.get("BENCH_MC_PROCS", "2"))
        dev_per = int(os.environ.get("BENCH_MC_DEV_PER_PROC", "4"))
        row = multichip_row(n_procs, dev_per)
        cap = {
            "n_devices": n_procs * dev_per,
            "rc": 0 if "multichip_skipped" not in row else 1,
            "ok": "multichip_skipped" not in row,
            "skipped": "multichip_skipped" in row,
            **row,
        }
        print(json.dumps(cap))
        hist_path = os.environ.get("BENCH_HISTORY")
        if hist_path and cap["ok"]:
            try:
                _history_gate_append(cap, hist_path)
            except Exception as e:  # noqa: BLE001 — never cost the capture
                sys.stderr.write(f"bench: history append failed: {e}\n")
        sys.exit(0 if cap["ok"] else 1)

    _ensure_live_backend()

    import jax

    backend = jax.default_backend()
    # BENCH_PROFILE=path: apply a tuned knob profile (written by
    # python -m dbscan_tpu.bench --tune) as tuned DEFAULTS — explicit
    # DBSCAN_* exports still win (config.Profile precedence). The
    # profile's tournament speedup is stamped on the capture so the
    # committed figure trends and gates next to the walls it bought.
    profile_fields = {}
    profile_path = os.environ.get("BENCH_PROFILE")
    if profile_path:
        from dbscan_tpu.config import Profile

        prof = Profile.load(profile_path)
        prof.apply()
        profile_fields = {
            "profile": os.path.basename(profile_path),
            "profile_workload": prof.workload,
        }
        spd = prof.meta.get("tuned_vs_default_speedup")
        if prof.backend not in ("unknown", backend):
            # profiles are per-backend by design: apply the knobs (the
            # operator asked), but NEVER stamp a foreign tournament's
            # speedup onto this backend's gated history population
            print(
                f"bench: profile {profile_path} was tuned on backend "
                f"{prof.backend!r} but this run is {backend!r} — "
                "applying its knobs, NOT stamping its speedup",
                file=sys.stderr,
            )
        elif spd is not None:
            profile_fields["tuned_vs_default_speedup"] = float(spd)
    pts = make_data(n)

    with tempfile.TemporaryDirectory() as tmp:
        data_path = os.path.join(tmp, "data.npz")
        out_path = os.path.join(tmp, "cpu.npz")
        np.savez(data_path, pts=pts[:cpu_n])

        # accelerator runs FIRST, alone — the driver's host-side phases
        # (partitioner, merge) are CPU-bound, so a concurrently-running
        # CPU baseline would contaminate the timed run
        use_pallas = os.environ.get("BENCH_PALLAS", "0") == "1"
        # the Pallas run rides the banded two-sweep structure
        # (ops/pallas_banded.py); the auto dense/banded width threshold is
        # tuned for the XLA engines, so force the banded route here
        pallas_extra = {"neighbor_backend": "banded"} if use_pallas else {}
        reps = int(os.environ.get("BENCH_REPS", "3"))
        try:
            model, dt, rep_obs = run_train(
                pts, maxpp, use_pallas=use_pallas, reps=reps, **pallas_extra
            )
        except jax.errors.JaxRuntimeError as e:
            # device-runtime deaths only: a deterministic host/config
            # error must surface, not trigger an hours-long CPU rerun
            # that hits it again
            if backend == "cpu":
                raise
            # worker died MID-RUN (init was fine): degrade the whole
            # capture to a real CPU measurement, not a missing JSON line
            _reexec_cpu(
                f"accelerator died mid-headline ({type(e).__name__})",
                cleanup_dir=tmp,
            )
        throughput = len(pts) / dt / 1e6

        from dbscan_tpu import Engine, train
        from dbscan_tpu.utils.ari import adjusted_rand_index

        # full-run label check: an INDEPENDENT second run of the whole
        # dataset at a different partitioning (different bucket widths,
        # halo routing, and merge order) must reproduce the timed run's
        # labels — this is the ari_full of the run whose throughput is
        # reported, not of a subset. The alt maxpp is guaranteed to
        # differ (halve when possible, else double).
        try:
            alt_model = train(
                pts,
                eps=EPS,
                min_points=MIN_POINTS,
                max_points_per_partition=(
                    maxpp // 2 if maxpp >= 4096 else maxpp * 2
                ),
                engine=Engine.ARCHERY,
                use_pallas=use_pallas,
                **pallas_extra,
            )
            # correctness cross-check: cluster the SAME cpu_n-point subset
            # on the accelerator (clustering a subset of a larger run
            # differs legitimately near borders, so comparing against
            # model.clusters[:n] would understate agreement)
            sub_model = train(
                pts[:cpu_n],
                eps=EPS,
                min_points=MIN_POINTS,
                max_points_per_partition=maxpp,
                engine=Engine.ARCHERY,
                use_pallas=use_pallas,
                **pallas_extra,
            )
        except jax.errors.JaxRuntimeError as e:
            if backend == "cpu":
                raise
            _reexec_cpu(
                f"accelerator died mid-cross-check ({type(e).__name__})",
                cleanup_dir=tmp,
            )
        # host-side scoring stays OUTSIDE the try: a host failure here
        # (e.g. MemoryError in ARI at huge N) must surface, not trigger
        # a CPU re-exec that discards the finished device measurement
        ari_full = adjusted_rand_index(model.clusters, alt_model.clusters)

        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--cpu-child", data_path, out_path],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE,
            timeout=3600,
        )
        if proc.returncode != 0:
            sys.stderr.write(proc.stderr.decode(errors="replace"))
            raise SystemExit(f"cpu baseline child failed ({proc.returncode})")
        cpu = np.load(out_path)
        cpu_throughput = float(cpu["n"]) / float(cpu["seconds"]) / 1e6

    ari = adjusted_rand_index(sub_model.clusters, cpu["clusters"])

    out = {
        "metric": "dbscan_2d_euclidean_throughput",
        "value": round(throughput, 4),
        "unit": "Mpoints/s",
        "vs_baseline": round(throughput / max(cpu_throughput, 1e-12), 3),
        "backend": backend,
        "n_points": n,
        "cpu_baseline_mpts": round(cpu_throughput, 4),
        "ari_vs_cpu": round(float(ari), 6),
        "ari_full": round(float(ari_full), 6),
        "n_clusters": model.n_clusters,
        "n_partitions": model.stats["n_partitions"],
        "seconds": round(dt, 3),
        "phases": _phases(model.stats),
        **profile_fields,  # tuned-profile provenance + gated speedup
        **rep_obs,  # upload/compute split (+ resident_hot when cosine)
        **_cellcc_fields("headline", model.stats),
    }
    if backend != "cpu" and os.environ.get("BENCH_MFU", "1") == "1":
        try:
            out.update(
                _mfu_fields(
                    "headline", pts, maxpp,
                    use_pallas=use_pallas, **pallas_extra,
                )
            )
        except Exception as e:  # noqa: BLE001 — supplementary, not fatal
            out["headline_mfu_failed"] = f"{type(e).__name__}"[:80]
    # Engineered-structure anchor rows (euclid / haversine / cosine) are ON
    # by default so the driver-side capture witnesses all three metric
    # paths, at backend-aware sizes: full scale on the accelerator, small
    # on the CPU fallback (which exists to stay honest, not fast). A wall
    # budget bounds the whole extras section — a slow tunnel day degrades
    # to explicit "<row>_skipped" markers instead of a driver timeout.
    on_cpu = backend == "cpu"
    t_rows = time.monotonic()
    budget = float(os.environ.get("BENCH_BUDGET_S", "1500"))
    anchor_rows = [
        (
            "anchor",
            "euclidean",
            "BENCH_ANCHOR",
            int(
                os.environ.get(
                    "BENCH_ANCHOR_N", "200000" if on_cpu else "10000000"
                )
            ),
            int(
                os.environ.get(
                    "BENCH_ANCHOR_MAXPP", "4096" if on_cpu else "131072"
                )
            ),
        ),
        (
            "haversine",
            "haversine",
            "BENCH_HAVERSINE",
            int(
                os.environ.get(
                    "BENCH_HAV_N", "100000" if on_cpu else "10000000"
                )
            ),
            int(
                os.environ.get(
                    "BENCH_HAV_MAXPP", "4096" if on_cpu else "131072"
                )
            ),
        ),
        # sparse BEFORE cosine (VERDICT r3 item 8): cosine is the budget
        # eater, and three rounds of driver captures ended with
        # "sparse_skipped" because it ran last
        (
            "sparse",
            "sparse",
            "BENCH_SPARSE",
            int(
                os.environ.get(
                    "BENCH_SPARSE_N", "30000" if on_cpu else "200000"
                )
            ),
            int(os.environ.get("BENCH_SPARSE_MAXPP", "4096")),
        ),
        (
            "cosine",
            "cosine",
            "BENCH_COSINE",
            int(
                os.environ.get(
                    "BENCH_COS_N", "50000" if on_cpu else "1000000"
                )
            ),
            int(os.environ.get("BENCH_COS_MAXPP", "8192")),
        ),
    ]
    # the budget must also bound a row that has not STARTED: predict each
    # row's cost from the headline run's measured rate (a slow-tunnel day
    # shows up there first) times a per-metric cost factor, and skip rows
    # whose estimate does not fit the remaining budget
    headline_rate = n / max(dt, 1e-9)  # points/s, hot
    anchor_reps = int(os.environ.get("BENCH_ANCHOR_REPS", "2")) + 1  # +warmup
    cos_reps = int(os.environ.get("BENCH_COS_REPS", "1")) + 1
    # sparse warm-up runs on a 20k subset (~5% of a rep), hence the 0.05
    sparse_reps = int(os.environ.get("BENCH_SPARSE_REPS", "1")) + 0.05
    cost_factor = {
        "euclidean": 2.0,
        "haversine": 5.0,
        "cosine": 40.0,
        "sparse": 20.0,
    }
    for prefix, kind, env_name, row_n, row_maxpp in anchor_rows:
        if os.environ.get(env_name, "1") == "0":
            continue
        remaining = budget - (time.monotonic() - t_rows)
        row_reps = {
            "sparse": sparse_reps,
            "cosine": cos_reps,
        }.get(kind, anchor_reps)
        # euclid adds one instrumented MFU run; cosine/sparse add a CPU
        # baseline child (bounded by its own budget-derived timeout, so
        # estimate half of that bound) — charge only sub-runs that will
        # actually execute
        if (
            kind == "euclidean"
            and not on_cpu
            and os.environ.get("BENCH_MFU", "1") == "1"
        ):
            row_reps += 1
        est = row_reps * row_n / headline_rate * cost_factor[kind]
        if (
            kind in ("cosine", "sparse")
            and not on_cpu
            and os.environ.get("BENCH_ROW_BASELINES", "1") != "0"
        ):
            est += (
                float(
                    os.environ.get(
                        "BENCH_ROW_BASELINE_TIMEOUT_S",
                        str(min(1800, 0.4 * budget)),
                    )
                )
                / 2
            )
        if remaining <= 0 or est > remaining:
            out[f"{prefix}_skipped"] = (
                "time_budget" if remaining <= 0 else "est_over_budget"
            )
            continue
        # one failing row must not take down the whole capture (the JSON
        # line with every other row is the round's official record)
        try:
            if kind == "sparse":
                out.update(sparse_row(prefix, row_n, maxpp=row_maxpp))
            else:
                out.update(
                    anchor_row(prefix, row_n, kind=kind, maxpp=row_maxpp)
                )
        except Exception as e:  # noqa: BLE001 — recorded, not swallowed
            sys.stderr.write(f"bench: {prefix} row failed: {e}\n")
            out[f"{prefix}_failed"] = f"{type(e).__name__}: {e}"[:200]
    # the 100M retry-resume campaign runs LAST and only on a live
    # accelerator: its legs can kill the tunneled worker, and every
    # other row must already be banked when that happens. Its legs are
    # subprocesses, so a worker death degrades to partial-progress
    # fields, never a lost capture.
    if os.environ.get("BENCH_100M", "0" if on_cpu else "1") == "1":
        try:
            out.update(m100_row())
        except Exception as e:  # noqa: BLE001 — recorded, not swallowed
            sys.stderr.write(f"bench: m100 row failed: {e}\n")
            out["m100_failed"] = f"{type(e).__name__}: {e}"[:200]
    # BENCH_HISTORY=path: gate this capture against the PRIOR history,
    # then append it only when green (dbscan_tpu/obs/bench_history.py +
    # obs/regress.py — same ingest/gate the root BENCH_*.json files go
    # through). Gate-before-append matters twice over: appending first
    # would put the fresh numbers inside their own baseline (diluting
    # the median), and a regressed capture, once ingested, widens the
    # history's spread until the noise-aware threshold covers the
    # regression for every later run. A flagged capture stays on stdout
    # as usual — ingest it manually after investigation
    # (`python -m dbscan_tpu.obs.bench_history <file>`).
    # Best-effort: a history IO failure must never cost the capture.
    hist_path = os.environ.get("BENCH_HISTORY")
    if hist_path:
        try:
            _history_gate_append(out, hist_path)
        except Exception as e:  # noqa: BLE001 — recorded, not fatal
            sys.stderr.write(f"bench: history append failed: {e}\n")
    # full record FIRST, compact summary line LAST: the driver captures a
    # bounded tail window, and r4's attribution fields pushed the single
    # JSON line past it (BENCH_r04.json "parsed": null) — the machine-
    # readable headline must be the final thing on stdout
    print(json.dumps(out))
    print(json.dumps(_compact_summary(out)))


def _history_gate_append(out: dict, hist_path: str) -> bool:
    """Gate one capture against the PRIOR bench history and append its
    normalized records only when green; returns True when appended.
    Gate-before-append is load-bearing: appending first would put the
    fresh numbers inside their own baseline (diluting the median), and
    a regressed capture, once ingested, widens the history's spread
    until the noise-aware threshold covers the regression for every
    later run. A flagged capture stays on stdout as usual — ingest it
    manually after investigation
    (`python -m dbscan_tpu.obs.bench_history <file>`)."""
    from dbscan_tpu.obs import bench_history
    from dbscan_tpu.obs import regress as obs_regress

    records = bench_history.normalize_capture(
        out, f"bench_live_{int(time.time())}", bench_history.git_rev()
    )
    verdict = obs_regress.compare(
        records, bench_history.load_history(hist_path)
    )
    if verdict["regressions"]:
        for e in verdict["regressions"]:
            sys.stderr.write(
                f"bench: {obs_regress.format_regression(e)}\n"
            )
        sys.stderr.write(
            f"bench: capture NOT appended to {hist_path} "
            "(regression gate failed)\n"
        )
        return False
    added, _ = bench_history.append_records(records, hist_path)
    sys.stderr.write(
        f"bench: {added} record(s) appended to {hist_path}\n"
    )
    return True


_COMPACT_SUFFIXES = (
    "_seconds",
    "_vs_baseline",
    "_ari",
    "_skipped",
    "_failed",
    "_mpts",
    "_chunks_done",
    "_chunks_total",
    "_legs",
    "_complete",
    # hot/cold rep tag (dbscan_tpu/obs): a compact line whose cosine
    # wall cannot be read without knowing whether the rep paid the
    # payload upload must carry the tag too
    "_resident_hot",
    # pull-pipeline overlap share (parallel/pipeline.py): rides the
    # compact line so tail-only captures still feed the regress gate
    "_pull_overlap_ratio",
    # graftshape containment figure (lint/shapecheck.py): observed HBM
    # peak / statically predicted peak, hard-capped <= 1.0 by regress
    "_hbm_pred_ratio",
    # devtime measured device-busy share of the rep wall
    # (obs/devtime.py): gates higher-better like the overlap ratio
    "_device_busy_frac",
    # device cellcc finalize: the whole-finalize wall and the CC sweep
    # count (parallel/cellgraph.py finalize_device) — both gated, so
    # tail-only captures still catch a finalize regression
    "_cellcc_finalize_s",
    "_cellcc_cc_iters",
    # shared window_cc propagation depth (ops/propagation.py) and the
    # autotuner's committed tuned-vs-default ratio — both gated
    "_prop_sweeps",
    "_vs_default_speedup",
)


def _compact_summary(out: dict) -> dict:
    """The tail-window-sized record: headline scalars plus each row's
    seconds/ARI/vs_baseline (and skip/fail/progress markers) only — no
    phase splits, no attribution fields."""
    compact = {
        k: out[k]
        for k in (
            "metric",
            "value",
            "unit",
            "vs_baseline",
            "backend",
            "n_points",
            "seconds",
            "ari_full",
            "ari_vs_cpu",
            "n_clusters",
            "pull_overlap_ratio",
            "hbm_pred_ratio",
        )
        if k in out
    }
    for k, v in out.items():
        if k in compact:
            continue
        if k.endswith(_COMPACT_SUFFIXES):
            compact[k] = v
    return compact


if __name__ == "__main__":
    main()
