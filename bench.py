"""Benchmark harness: distributed DBSCAN throughput on the local accelerator
vs a CPU baseline of the SAME pipeline (XLA-CPU), plus ARI cross-check.

Prints exactly ONE JSON line:
  {"metric": ..., "value": <Mpoints/s on accelerator>, "unit": "Mpoints/s",
   "vs_baseline": <accelerator/cpu speedup>, ...extras}

The reference publishes no numbers (BASELINE.md); the baseline here is the
same workload on XLA-CPU in a subprocess — a strictly stronger baseline than
Spark-CPU's scalar JVM loops for this O(B^2)-per-partition algorithm.

Env knobs: BENCH_N (points, default 1M), BENCH_MAXPP (max points per
partition on the accelerator, default 262144 — large partitions route the
fine-grid banded engine and amortize the halo duplication and host merge;
measured fastest at 1M on v5e), BENCH_CPU_MAXPP (baseline partition size,
default 2048 — the CPU's own sweet spot; the quadratic per-partition cost
favors smaller partitions there), BENCH_CPU_N (baseline points, default
min(N, 100k)), BENCH_PALLAS (1 = route the accelerator run through the
streaming Pallas kernels; the CPU baseline always uses the XLA path).
"""

import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

EPS = 0.35
MIN_POINTS = 10


def make_data(n: int) -> np.ndarray:
    """Clustered + noise workload (moons/blobs-style per BASELINE.json
    configs[0]), spread over a wide area so spatial partitioning engages."""
    rng = np.random.default_rng(42)
    n_clusters = max(4, n // 25000)
    centers = rng.uniform(-60, 60, size=(n_clusters, 2))
    per = (n * 9 // 10) // n_clusters
    pts = np.concatenate(
        [rng.normal(c, 0.8, size=(per, 2)) for c in centers]
        + [rng.uniform(-70, 70, size=(n - per * n_clusters, 2))]
    ).astype(np.float64)
    rng.shuffle(pts)
    return pts


def run_train(pts, maxpp, use_pallas=False, reps=1):
    from dbscan_tpu import Engine, train

    kw = dict(
        eps=EPS,
        min_points=MIN_POINTS,
        max_points_per_partition=maxpp,
        engine=Engine.ARCHERY,
        use_pallas=use_pallas,
    )
    # compile warm-up on identical shapes, then best-of-reps timed runs:
    # the TPU is reached over a shared tunnel whose transfer latency
    # fluctuates by >3x between runs, so a single timing is a lottery —
    # the minimum is the reproducible peak-throughput figure
    train(pts, **kw)
    dt = float("inf")
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        model = train(pts, **kw)
        dt = min(dt, time.perf_counter() - t0)
    return model, dt


def child_cpu(data_path: str, out_path: str, maxpp: int) -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    pts = np.load(data_path)["pts"]
    model, dt = run_train(pts, maxpp)
    np.savez(out_path, clusters=model.clusters, seconds=dt, n=len(pts))


def main() -> None:
    n = int(os.environ.get("BENCH_N", "1000000"))
    maxpp = int(os.environ.get("BENCH_MAXPP", "262144"))
    cpu_maxpp = int(os.environ.get("BENCH_CPU_MAXPP", "2048"))
    cpu_n = int(os.environ.get("BENCH_CPU_N", str(min(n, 100000))))

    if len(sys.argv) >= 4 and sys.argv[1] == "--cpu-child":
        child_cpu(sys.argv[2], sys.argv[3], cpu_maxpp)
        return

    import jax

    backend = jax.default_backend()
    pts = make_data(n)

    with tempfile.TemporaryDirectory() as tmp:
        data_path = os.path.join(tmp, "data.npz")
        out_path = os.path.join(tmp, "cpu.npz")
        np.savez(data_path, pts=pts[:cpu_n])

        # accelerator runs FIRST, alone — the driver's host-side phases
        # (partitioner, merge) are CPU-bound, so a concurrently-running
        # CPU baseline would contaminate the timed run
        use_pallas = os.environ.get("BENCH_PALLAS", "0") == "1"
        reps = int(os.environ.get("BENCH_REPS", "3"))
        model, dt = run_train(pts, maxpp, use_pallas=use_pallas, reps=reps)
        throughput = len(pts) / dt / 1e6

        # correctness cross-check: cluster the SAME cpu_n-point subset on the
        # accelerator (clustering a subset of a larger run differs
        # legitimately near borders, so comparing against model.clusters[:n]
        # would understate agreement)
        from dbscan_tpu import Engine, train

        sub_model = train(
            pts[:cpu_n],
            eps=EPS,
            min_points=MIN_POINTS,
            max_points_per_partition=maxpp,
            engine=Engine.ARCHERY,
            use_pallas=use_pallas,
        )

        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--cpu-child", data_path, out_path],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE,
            timeout=3600,
        )
        if proc.returncode != 0:
            sys.stderr.write(proc.stderr.decode(errors="replace"))
            raise SystemExit(f"cpu baseline child failed ({proc.returncode})")
        cpu = np.load(out_path)
        cpu_throughput = float(cpu["n"]) / float(cpu["seconds"]) / 1e6

    from dbscan_tpu.utils.ari import adjusted_rand_index

    ari = adjusted_rand_index(sub_model.clusters, cpu["clusters"])

    print(
        json.dumps(
            {
                "metric": "dbscan_2d_euclidean_throughput",
                "value": round(throughput, 4),
                "unit": "Mpoints/s",
                "vs_baseline": round(throughput / max(cpu_throughput, 1e-12), 3),
                "backend": backend,
                "n_points": n,
                "cpu_baseline_mpts": round(cpu_throughput, 4),
                "ari_vs_cpu": round(float(ari), 6),
                "n_clusters": model.n_clusters,
                "n_partitions": model.stats["n_partitions"],
                "seconds": round(dt, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
